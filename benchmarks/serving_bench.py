"""Serving latency under load: the first benchmark in this repo that
measures LATENCY, not training throughput.

Drives the continuous-batching engine (:mod:`distkeras_tpu.serving`) with
two canonical load shapes:

- **closed-loop**: C concurrent clients, each submitting its next request
  the moment the previous one completes — measures saturated-engine
  behavior (slot occupancy, tokens/sec goodput);
- **open-loop**: Poisson arrivals at an offered rate λ req/s regardless
  of completions — measures the latency/load curve an SLO cares about
  (p50/p95/p99 TTFT, queue growth, backpressure rejects when λ exceeds
  capacity).

``--prefix-ratio R`` (with ``--prefix-cache-mb``) switches the workload
to **shared-prefix traffic**: every prompt is a fixed length
(``--prompt-len``), the first ``R`` of it drawn from ``--prefix-count``
distinct "system prompts" and the tail random — the synthetic version of
template-dominated production traffic. The report then carries the
prefix-cache hit rate and the split TTFT series (``queue_wait`` vs
``prefill_device``) alongside the latency percentiles, so a cache-on vs
cache-off pair of runs shows exactly what the hits buy.

Also verifies the two engine invariants the subsystem is built on, so a
CPU demo run IS the acceptance test:

1. admission never retraces decode — exactly ONE compiled decode
   executable after the whole run (compile-count probe);
2. continuous-batched greedy streams match one-shot ``generate()``
   token-for-token for the same prompts — including chunked
   (``--prefill-chunk``) and prefix-cached admission.

``--paged``/``--kv-pool-mb`` switch the engine to **paged KV** (one
block pool for decode slots and the prefix cache; oversubscription with
preempt-and-requeue), and ``--slot-sweep N1,N2,...`` measures the paged
headline directly: at a FIXED KV byte budget, which slot counts sustain
full completion, at what saturated p99 ITL, and how many resident
tokens per MiB the budget actually carried (``kv_tokens_per_mib``).
Pair with a dense run at the same bytes (``--max-context`` fixes its
per-slot cache) for the capacity-multiplier comparison.

``--record-history`` appends the run's headline numbers (TTFT/ITL
percentiles, goodput, hit rate — and the sweep's max-sustained-slots /
tokens-per-MiB rows) to ``bench_history.json`` under ``serving/...``
keys (``serving/paged_*`` for paged runs);
``scripts/check_bench_regression.py`` diffs them against the prior
same-config run (direction-aware: latency up = bad).

``--speculate`` (optionally ``--draft-model``/``--spec-k``) turns on
**speculative decoding**: a draft model proposes K tokens per engine
tick and ONE batched target call verifies them, so a tick commits up to
K tokens per greedy slot. The default draft is the target itself — the
sanity config where acceptance is ~100% and the measured speedup
isolates speculation's GEMV→GEMM/dispatch restructuring. The run arms
the ``RecompileAuditor`` and asserts draft, verify, and the fallback
decode each compiled exactly once; parity against ``generate()`` is
checked as always (committed tokens are always draft tokens, so the
sanity config's chain is bitwise the sequential one). Reports the
accept rate, per-mode ``spec_*`` counters, and — with
``--record-history`` — ``serving/spec_*`` history rows.

``--mesh`` / ``--mesh-shape tp=N`` (with ``--force-host-devices N`` on a
CPU host) runs the engine **GSPMD tensor-parallel**: params laid out by
their logical axes, KV heads-sharded, every callable pinned to explicit
in/out shardings. The run arms the ``RecompileAuditor`` (compile-once
per callable, sharded layouts and all) and the standard parity check
against the UNSHARDED ``generate()`` reference becomes the
sharded-vs-unsharded token-identity proof. ``--record-history`` writes
``serving/sharded_<model>_tpN/...`` rows under the same strict
``--only serving/`` CI gate:

    JAX_PLATFORMS=cpu python benchmarks/serving_bench.py --mode closed \
        --mesh-shape tp=2 --force-host-devices 2 --requests 24

``--replicas N`` (N >= 2) swaps the single engine for an **in-process
cluster**: N engines behind the supervised router
(:mod:`distkeras_tpu.serving.cluster`), with the load driven through TCP
clients against the router's front port — latency numbers then include
the router hop, and the report carries router counters (retries,
affinity picks, streams lost) plus per-replica restarts.
``--chaos-kill-at S`` additionally SIGKILL-equivalently kills replica r0
``S`` seconds into each load phase: the run asserts the cluster contract
— no zero-streamed request fails (retried on a survivor), the
supervisor restarts the corpse, and the fleet is whole again at the end.

``--slo`` runs the **fleet telemetry + SLO acceptance** phases on a
3-replica fleet: a no-push baseline vs the push plane's goodput
overhead, the router's fleet-merged TTFT/ITL p99 diffed against an
offline recompute from every replica's raw samples (must agree within
one histogram bucket width — the mergeable-histogram exactness
contract end to end), and an ``inject_latency`` breach that must drive
the burn-rate engine ``ok -> page`` with exemplar trace ids and
recover once cleared. ``--record-history`` writes ``serving/slo_*``
rows (push overhead, aggregation staleness, burn cost, time-to-page).

Run (CPU):
    JAX_PLATFORMS=cpu python benchmarks/serving_bench.py \
        --mode both --requests 24 --slots 4 --metrics-out /tmp/serve.jsonl
    # shared-prefix workload, cache on:
    JAX_PLATFORMS=cpu python benchmarks/serving_bench.py --mode closed \
        --seq-len 128 --prompt-len 96 --prefix-ratio 0.75 \
        --prefix-cache-mb 16 --requests 24
    # 2-replica cluster with a mid-run replica kill:
    JAX_PLATFORMS=cpu python benchmarks/serving_bench.py --mode closed \
        --replicas 2 --chaos-kill-at 2 --requests 24
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import zlib

import numpy as np


def _force_host_devices(n):
    """Set the XLA forced-device-count flag BEFORE anything imports jax
    (stdlib-only on purpose: importing distkeras_tpu would initialize
    jax first and make the flag a no-op). Single-threaded Eigen rides
    along: virtual devices share one intra-op pool and the sharded
    engine's per-layer all-reduces can deadlock the rendezvous without
    it (see utils.platform.ensure_virtual_cpu_flags)."""
    if not n:
        return
    import os
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    flags += f" --xla_force_host_platform_device_count={int(n)}"
    if "--xla_cpu_multi_thread_eigen" not in flags:
        flags += " --xla_cpu_multi_thread_eigen=false"
    os.environ["XLA_FLAGS"] = flags.strip()
    # Forced HOST devices only exist on the CPU platform (same pin as
    # run.py's --force-host-devices).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _mesh(args):
    """The serving mesh --mesh/--mesh-shape ask for (cached on args so
    sweep/cluster paths building fresh engines reuse ONE mesh)."""
    if not (args.mesh or args.mesh_shape):
        return None
    if getattr(args, "_mesh", None) is None:
        from distkeras_tpu.parallel.mesh import (
            parse_mesh_shape, serving_mesh,
        )

        shape = (parse_mesh_shape(args.mesh_shape)
                 if args.mesh_shape else None)
        args._mesh = serving_mesh(shape)
    return args._mesh


def _model(args):
    from distkeras_tpu.models.bert import gpt_small, gpt_tiny

    model = (gpt_tiny(seq_len=args.seq_len, vocab_size=args.vocab)
             if args.model == "gpt_tiny" else gpt_small(seq_len=args.seq_len))
    return model, model.init(0)


def _speculating(args) -> bool:
    return bool(args.speculate or args.draft_model)


def _draft(args, model, variables):
    """The draft pair for --speculate. Default (no --draft-model, or the
    same name as --model) is the **draft==target sanity config**: the
    draft IS the target — acceptance ~100%, so the measured speedup
    isolates what speculation's restructuring buys (K scanned draft
    steps + ONE K-wide verify dispatch vs K one-token dispatches)
    from draft-model quality. A different name builds that zoo model at
    the target's vocab with seed-init weights."""
    if not _speculating(args):
        return None, None
    name = args.draft_model or args.model
    if name == args.model:
        return model, variables
    from distkeras_tpu.models.bert import gpt_small, gpt_tiny

    # Always at the TARGET's vocab: proposals are target token ids.
    draft = (gpt_tiny(seq_len=args.seq_len, vocab_size=model.output_dim)
             if name == "gpt_tiny"
             else gpt_small(seq_len=args.seq_len,
                            vocab_size=model.output_dim))
    return draft, draft.init(args.seed)


def _make_engine(args, model, variables, metrics=None, trace_store=None,
                 slots=None, tenant_quotas=None, tenant_weights=None,
                 quota_burst_s=2.0, pipeline_depth=None, arm=False,
                 kv_host_tier_mb=0.0, constrained=False):
    from distkeras_tpu.serving import ServingEngine, ServingMetrics

    paged = args.paged or args.kv_pool_mb > 0
    draft_model, draft_variables = _draft(args, model, variables)
    mesh = _mesh(args)
    auditor = None
    if arm or draft_model is not None or mesh is not None:
        # Speculative AND sharded runs arm the auditor: the acceptance
        # bar is not just the throughput/parity number but "while every
        # callable (draft/verify/fallback decode, sharded layouts
        # pinned) stays at ONE executable" — a retrace raises mid-run
        # instead of silently eating the win.
        from distkeras_tpu.telemetry import RecompileAuditor

        auditor = RecompileAuditor()
    return ServingEngine(
        model, variables, slots=slots or args.slots,
        max_queue=args.max_queue,
        metrics=metrics or ServingMetrics(),
        prefill_chunk=args.prefill_chunk,
        prefix_cache_mb=0.0 if paged else args.prefix_cache_mb,
        prefix_block_tokens=args.prefix_block,
        paged=paged,
        kv_pool_mb=args.kv_pool_mb or (8.0 if paged else 0.0),
        kv_block_tokens=args.kv_block,
        max_context=args.max_context,
        draft_model=draft_model, draft_variables=draft_variables,
        spec_k=args.spec_k, mesh=mesh,
        pipeline_depth=(args.pipeline_depth if pipeline_depth is None
                        else pipeline_depth),
        kv_host_tier_mb=kv_host_tier_mb, constrained=constrained,
        auditor=auditor, arm_auditor_after_warmup=auditor is not None,
        trace_store=trace_store,
        tenant_quotas=tenant_quotas, tenant_weights=tenant_weights,
        quota_burst_s=quota_burst_s,
        slo_s=args.slo_ms / 1e3 if args.slo_ms else None)


def _build(args):
    from distkeras_tpu.serving import ServingMetrics
    from distkeras_tpu.telemetry import MetricsRegistry, TraceStore
    from distkeras_tpu.tracing import MetricStream

    model, variables = _model(args)
    registry = MetricsRegistry()
    stream = (MetricStream.to_jsonl(args.metrics_out, registry=registry)
              if args.metrics_out else None)
    trace_store = TraceStore(4096) if args.request_trace_out else None
    engine = _make_engine(args, model, variables,
                          metrics=ServingMetrics(stream, registry=registry),
                          trace_store=trace_store)
    return model, variables, engine, stream


def _prompts(args, n, salt=0):
    # ``salt`` varies per phase so a --mode both run doesn't replay the
    # closed phase's exact prompts in the open phase — with a prefix
    # cache that would match the FULL prompts (cached whole in phase one)
    # and report a hit rate far above the configured --prefix-ratio. The
    # shared prefixes themselves must NOT vary: draw them pre-salt.
    rng = np.random.default_rng(args.seed)
    if args.prefix_ratio > 0:
        # Shared-prefix workload: fixed-length prompts whose first
        # prefix_ratio share is one of --prefix-count "system prompts"
        # (round-robin) and whose tail is per-request random. One prompt
        # length keeps the parity cross-check at one generate() compile.
        plen = args.prompt_len or max(args.seq_len - args.new_tokens - 1, 2)
        plen = min(plen, args.seq_len - args.new_tokens)
        pre_len = min(int(plen * args.prefix_ratio), plen - 1)
        prefixes = [rng.integers(0, args.vocab, size=pre_len).tolist()
                    for _ in range(max(1, args.prefix_count))]
        tail_rng = np.random.default_rng(args.seed + 7919 * salt)
        return [prefixes[i % len(prefixes)]
                + tail_rng.integers(0, args.vocab,
                                    size=plen - pre_len).tolist()
                for i in range(n)]
    if args.prompt_len:
        rng = np.random.default_rng(args.seed + 7919 * salt)
        return [rng.integers(0, args.vocab, size=args.prompt_len).tolist()
                for _ in range(n)]
    # Lengths from a small fixed set: the engine handles any length, but
    # the parity cross-check's generate() compiles once per distinct
    # prompt shape — a handful of lengths keeps a CPU demo run fast.
    # Salted like the branches above (same shapes, fresh tokens), so a
    # cache-enabled --mode both run doesn't replay phase one's prompts.
    rng = np.random.default_rng(args.seed + 7919 * salt)
    pool = [k for k in (3, 5, 8, 13) if k < args.seq_len // 2] or [3]
    lens = rng.choice(pool, size=n)
    return [rng.integers(0, args.vocab, size=int(k)).tolist() for k in lens]


async def _closed_loop(engine, prompts, args):
    """C clients, each chaining requests back-to-back."""
    results: list[tuple[list[int], list[int]]] = []
    it = iter(prompts)

    async def client():
        for p in it:
            req = engine.submit(p, args.new_tokens)
            toks = await req.result()
            results.append((p, toks))

    await asyncio.gather(*(client() for _ in range(args.clients)))
    return results


async def _open_loop(engine, prompts, args):
    """Poisson arrivals at --rate req/s; rejects counted, not retried."""
    rng = np.random.default_rng(args.seed + 1)
    from distkeras_tpu.serving import QueueFullError

    pending, rejects, results = [], 0, []
    for p in prompts:
        try:
            pending.append((p, engine.submit(p, args.new_tokens)))
        except QueueFullError:
            rejects += 1
        await asyncio.sleep(float(rng.exponential(1.0 / args.rate)))
    for p, req in pending:
        results.append((p, await req.result()))
    return results, rejects


def _check_parity(model, variables, results, new_tokens):
    from distkeras_tpu.inference.generate import generate

    mismatches = 0
    seen: dict[tuple, list[int]] = {}
    for p, got in results:
        key = tuple(p)
        if key not in seen:
            seen[key] = generate(model, variables, np.asarray([p], np.int32),
                                 new_tokens, greedy=True)[0].tolist()
        mismatches += got != seen[key]
    return mismatches


async def _cluster_bench(args, report, roles=None):
    """Drive the load phases through an in-process router + N replicas.

    End-to-end numbers (client-observed TTFT/ITL/latency, through the
    router hop), router/supervisor counters, and — with
    ``--chaos-kill-at`` — the cluster contract asserted under a
    mid-phase replica kill. ``roles`` (a per-index "prefill"/"decode"
    list) runs the fleet DISAGGREGATED: the router prefills each prompt
    on a prefill replica and decode replicas adopt the KV blocks — the
    report then carries the fleet's migration counters."""
    import time as _time

    from distkeras_tpu.serving import (
        LocalReplica, QueueFullError, ServingClient, ServingCluster,
        ServingMetrics,
    )
    from distkeras_tpu.serving.client import ServerError
    from distkeras_tpu.serving.metrics import percentile
    from distkeras_tpu.telemetry import MetricsRegistry
    from distkeras_tpu.tracing import MetricStream

    model, variables = _model(args)
    registry = MetricsRegistry()
    streams = []

    def replica(i):
        def build():
            metrics = None
            if args.metrics_out:
                # One JSONL series per replica (engines cannot share a
                # stream), suffixed like run.py's cluster mode. A
                # restarted replica reopens (and restarts) its file.
                path = f"{args.metrics_out}.r{i}"
                stream = MetricStream.to_jsonl(path)
                streams.append((path, stream))
                metrics = ServingMetrics(stream)
            return _make_engine(args, model, variables, metrics=metrics)

        return LocalReplica(build)

    router_kwargs = {"affinity_tokens": args.prefix_block}
    if roles:
        # Hand off any prompt holding at least one KV block — the bench
        # drives fixed prompt lengths, so the threshold must track the
        # block size, not the affinity prefix.
        router_kwargs["min_handoff_tokens"] = args.kv_block
    cluster = ServingCluster(
        replica, args.replicas, registry=registry, roles=roles,
        router_kwargs=router_kwargs,
        supervisor_kwargs=dict(health_interval_s=0.1, base_delay_s=0.2))
    all_results = []
    async with cluster:
        port = cluster.port
        modes = ["closed", "open"] if args.mode == "both" else [args.mode]
        for phase, mode in enumerate(modes):
            prompts = _prompts(args, args.requests, phase)
            results, lost, rejects, dones = [], [], 0, []

            async def one(c, p):
                nonlocal rejects
                streamed = []
                gaps = []
                # Client-side clocks: TTFT/ITL/latency as the CLIENT
                # sees them — router hop, pick-wait, and any mid-request
                # retry included (the replica-reported done-record
                # timings would hide exactly the penalties the cluster
                # and chaos modes exist to measure). ITL gaps are what
                # the disaggregated comparison is ABOUT: prefill
                # stealing decode ticks shows up as p99 inter-token
                # spikes on every in-flight stream.
                t_sub = _time.monotonic()
                t_first = t_last = None

                def on_token(tok):
                    nonlocal t_first, t_last
                    now = _time.monotonic()
                    if t_first is None:
                        t_first = now
                    else:
                        gaps.append(now - t_last)
                    t_last = now
                    streamed.append(tok)

                try:
                    done = await c.generate(p, args.new_tokens,
                                            on_token=on_token)
                    t_done = _time.monotonic()
                    results.append((p, done["tokens"]))
                    dones.append({
                        "ttft_s": (t_first or t_done) - t_sub,
                        "latency_s": t_done - t_sub,
                        "itl": gaps,
                        "kv_migration": done.get("kv_migration"),
                    })
                except QueueFullError:
                    rejects += 1
                except (ServerError, ConnectionError) as e:
                    lost.append({"streamed": len(streamed),
                                 "error": str(e)})

            chaos_task = None
            if args.chaos_kill_at is not None:
                async def chaos():
                    await asyncio.sleep(args.chaos_kill_at)
                    await cluster.replicas["r0"].handle.kill()

                chaos_task = asyncio.create_task(chaos())
            t0 = _time.monotonic()
            if mode == "closed":
                it = iter(prompts)

                async def client():
                    async with ServingClient("127.0.0.1", port) as c:
                        for p in it:
                            await one(c, p)

                await asyncio.gather(
                    *(client() for _ in range(args.clients)))
            else:
                arr = np.random.default_rng(args.seed + 1)
                tasks = []

                async def solo(p):
                    async with ServingClient("127.0.0.1", port) as c:
                        await one(c, p)

                for p in prompts:
                    tasks.append(asyncio.create_task(solo(p)))
                    await asyncio.sleep(
                        float(arr.exponential(1.0 / args.rate)))
                await asyncio.gather(*tasks)
            elapsed = _time.monotonic() - t0
            if chaos_task is not None:
                await chaos_task
            done_tokens = sum(len(t) for _, t in results)
            sec = {
                "completed": len(results),
                "lost_mid_stream": len(lost),
                "rejected_queue_full": rejects,
                "wall_s": round(elapsed, 3),
                "goodput_tokens_per_sec": round(done_tokens / elapsed, 2),
            }
            for key, field in (("ttft", "ttft_s"),
                               ("latency", "latency_s")):
                xs = [d[field] for d in dones]
                if xs:
                    sec[f"{key}_p50_s"] = round(percentile(xs, 50), 6)
                    sec[f"{key}_p99_s"] = round(percentile(xs, 99), 6)
            all_gaps = [g for d in dones for g in d.get("itl", ())]
            if all_gaps:
                sec["itl_p50_s"] = round(percentile(all_gaps, 50), 6)
                sec["itl_p99_s"] = round(percentile(all_gaps, 99), 6)
            migs = [d["kv_migration"] for d in dones
                    if d.get("kv_migration")]
            if migs:
                sec["kv_migrations"] = sum(
                    1 for m in migs if "fallback" not in m)
                sec["kv_migration_fallbacks"] = sum(
                    1 for m in migs if "fallback" in m)
                sec["kv_migration_bytes"] = sum(
                    int(m.get("bytes") or 0) for m in migs)
            report[mode] = sec
            all_results.extend(results)
            # The chaos contract, part 1: idempotent work never fails —
            # every lost stream had already delivered tokens.
            zero_streamed_lost = [e for e in lost if e["streamed"] == 0]
            assert not zero_streamed_lost, (
                f"{len(zero_streamed_lost)} zero-streamed requests failed "
                f"instead of being retried: {zero_streamed_lost}")
        if args.chaos_kill_at is not None:
            # Part 2: the supervisor restores the fleet and the corpse
            # rejoined routing.
            deadline = _time.monotonic() + 120
            while cluster.supervisor.ready_count < args.replicas:
                assert _time.monotonic() < deadline, "restart never happened"
                await asyncio.sleep(0.05)
            assert sum(r.restarts
                       for r in cluster.replicas.values()) >= 1
        report["cluster"] = {
            "replicas": args.replicas,
            "chaos_kill_at": args.chaos_kill_at,
            "restarts": {rid: info.restarts
                         for rid, info in cluster.replicas.items()},
            "router": {
                k: v.get("value")
                for k, v in registry.snapshot().items()
                if k.startswith(("router_", "cluster_"))
            },
        }
        # Every live replica still holds the one-executable invariant.
        compiles = {
            rid: info.handle.engine.decode_compile_count()
            for rid, info in cluster.replicas.items()
            if info.handle.engine is not None
        }
        report["cluster"]["decode_compile_count"] = compiles
        assert all(c in (1, -1, 0) for c in compiles.values()), compiles
        if roles:
            # Fleet migration rollup, read straight off the in-process
            # engines (the same counters metricsz/healthz export).
            snap = registry.snapshot()
            fleet = {
                "roles": {"prefill": roles.count("prefill"),
                          "decode": roles.count("decode")},
                "migrations": 0, "fallbacks": 0, "bytes_moved": 0,
                "exports": 0,
                "router_handoffs": snap.get(
                    "router_kv_handoffs_total", {}).get("value", 0),
                "router_handoff_fallbacks": snap.get(
                    "router_kv_handoff_fallbacks_total", {}).get(
                        "value", 0),
            }
            for info in cluster.replicas.values():
                eng = getattr(info.handle, "engine", None)
                if eng is None:
                    continue
                fleet["migrations"] += eng.metrics.kv_migrations
                fleet["fallbacks"] += eng.metrics.kv_migration_fallbacks
                fleet["bytes_moved"] += eng.metrics.kv_migration_bytes
                fleet["exports"] += eng.metrics.kv_exports
            report["disagg"] = fleet
    for _, stream in streams:
        stream.close()
    if args.metrics_out:
        report["cluster"]["metrics_out"] = sorted(
            {path for path, _ in streams})
    return model, variables, all_results


async def _qos_phase(engine, args, tenants, rates, salt):
    """One open-loop phase: every tenant submits Poisson traffic at its
    own rate concurrently. Returns per-tenant outcome lists — TTFTs for
    completions, typed error-code counts for rejects."""
    from distkeras_tpu.serving import ServingError, TenantOverQuota

    prompts = _prompts(args, args.requests, salt=salt)
    out = {t: {"ttft": [], "sheds": {}, "completed": 0, "errors": {}}
           for t in tenants}
    task = asyncio.create_task(engine.run())
    # Warm the compiled programs before the clock matters: phase A and
    # phase B must both measure steady-state TTFT, not who paid jit.
    await engine.submit(prompts[0], args.new_tokens,
                        tenant="__warmup__").result()

    async def tenant_load(tenant, qps, n):
        rec = out[tenant]
        pending = []
        # Stable per-tenant salt: Python's hash() is randomized per
        # process and would make the recorded qos rows irreproducible.
        tsalt = zlib.crc32(tenant.encode())
        trng = np.random.default_rng(args.seed + salt + tsalt % 9973)
        for i in range(n):
            p = prompts[(i + tsalt) % len(prompts)]
            try:
                pending.append(engine.submit(p, args.new_tokens,
                                             tenant=tenant))
            except TenantOverQuota:
                rec["sheds"]["tenant_over_quota"] = (
                    rec["sheds"].get("tenant_over_quota", 0) + 1)
            except ServingError as e:
                rec["sheds"][e.code] = rec["sheds"].get(e.code, 0) + 1
            await asyncio.sleep(float(trng.exponential(1.0 / qps)))
        for req in pending:
            try:
                await req.result()
                rec["completed"] += 1
                if req.ttft is not None:
                    rec["ttft"].append(req.ttft)
            except ServingError as e:
                rec["errors"][e.code] = rec["errors"].get(e.code, 0) + 1

    await asyncio.gather(*(
        tenant_load(t, qps, n) for t, (qps, n) in rates.items()))
    engine.shutdown(drain=True)
    await task
    return out


async def _qos_bench(args, model, variables, report):
    """The adversarial multi-tenant workload: N tenants share one
    engine; phase A (baseline) has every tenant offering its fair
    share, phase B (flood) has ONE hot tenant offering
    ``--hot-tenant-qps`` (default 10x fair) while the others keep their
    baseline load. With per-tenant quotas + DRR fair queueing, the
    flood must be shed as typed per-tenant rejects at submit and the
    OTHER tenants' p99 TTFT must hold (``--qos-max-degradation`` bounds
    the allowed ratio; the acceptance run uses 1.25)."""
    from distkeras_tpu.serving.metrics import percentile

    tenants = [f"t{i}" for i in range(args.tenants)]
    hot = tenants[0]
    fair_qps = args.rate / args.tenants
    hot_qps = args.hot_tenant_qps or 10.0 * fair_qps
    # ONE TENANT=VALUE parser repo-wide (run.py owns it).
    from distkeras_tpu.run import _parse_tenant_rates

    quotas = _parse_tenant_rates(args.tenant_quota, "--tenant-quota") or {}
    if not quotas:
        # Default: every tenant's token budget is DOUBLE its fair share
        # of the offered token rate, with a 4-second burst bucket —
        # honest Poisson traffic (bursty by nature) never touches it,
        # a 10x flood is shed at submit within one burst window.
        per_tenant = 2.0 * fair_qps * args.new_tokens
        quotas = {t: per_tenant for t in tenants}
    n_each = max(args.requests // args.tenants, 8)

    def build():
        return _make_engine(args, model, variables,
                            tenant_quotas=quotas, quota_burst_s=4.0)

    phases = {}
    for phase, hot_rate in (("baseline", fair_qps), ("flood", hot_qps)):
        engine = build()
        rates = {t: (fair_qps, n_each) for t in tenants}
        rates[hot] = (hot_rate,
                      n_each if phase == "baseline"
                      else max(int(n_each * hot_rate / fair_qps), n_each))
        phases[phase] = await _qos_phase(
            engine, args, tenants, rates,
            salt=101 if phase == "baseline" else 202)

    sec = {"tenants": args.tenants, "hot_tenant": hot,
           "fair_qps": round(fair_qps, 3), "hot_qps": round(hot_qps, 3),
           "quota_tokens_per_s": {t: quotas.get(t) for t in tenants}}
    for phase, data in phases.items():
        others = [x for t in tenants if t != hot for x in data[t]["ttft"]]
        psec = {
            "completed": {t: data[t]["completed"] for t in tenants},
            "sheds": {t: data[t]["sheds"] for t in tenants
                      if data[t]["sheds"]},
            "errors": {t: data[t]["errors"] for t in tenants
                       if data[t]["errors"]},
        }
        if others:
            psec["ttft_p50_others_s"] = round(percentile(others, 50), 6)
            psec["ttft_p99_others_s"] = round(percentile(others, 99), 6)
        if data[hot]["ttft"]:
            psec["ttft_p99_hot_s"] = round(
                percentile(data[hot]["ttft"], 99), 6)
        sec[phase] = psec
    base_p99 = sec["baseline"].get("ttft_p99_others_s")
    flood_p99 = sec["flood"].get("ttft_p99_others_s")
    if base_p99 and flood_p99:
        sec["ttft_degradation_ratio"] = round(flood_p99 / base_p99, 4)
    report["qos"] = sec

    # The QoS contract, asserted: every shed is a TYPED per-tenant
    # reject (never a generic failure), and honest tenants are never
    # shed at all — the flood lands exclusively on the flooder.
    for phase, data in phases.items():
        for t in tenants:
            bad = {k: v for k, v in data[t]["sheds"].items()
                   if k != "tenant_over_quota"}
            assert not bad, (f"{phase}: tenant {t} shed with non-quota "
                             f"codes {bad}")
            assert not data[t]["errors"], (
                f"{phase}: tenant {t} saw mid-stream errors "
                f"{data[t]['errors']} — quota must reject at submit, "
                f"never kill an admitted stream")
            if t != hot:
                assert not data[t]["sheds"], (
                    f"{phase}: honest tenant {t} was shed "
                    f"{data[t]['sheds']} — the flood leaked")
    assert phases["flood"][hot]["sheds"].get("tenant_over_quota", 0) > 0, \
        "the flood was never shed — quota not engaged (raise " \
        "--hot-tenant-qps or lower the quota)"
    if args.qos_max_degradation and base_p99 and flood_p99:
        ratio = flood_p99 / base_p99
        assert ratio <= args.qos_max_degradation, (
            f"other tenants' p99 TTFT degraded {ratio:.2f}x under the "
            f"flood (allowed {args.qos_max_degradation}x)")


def _parse_workload_mix(spec: str) -> dict[str, int]:
    """``generate:8,sample:4,score:6[,embed:2]`` -> {kind: count}."""
    out: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        kind, sep, cnt = part.partition(":")
        kind = kind.strip()
        if kind not in ("generate", "sample", "score", "embed"):
            raise SystemExit(
                f"--workload-mix: unknown kind {kind!r} (expected "
                f"generate/sample/score/embed)")
        try:
            n = int(cnt)
        except ValueError:
            raise SystemExit(
                f"--workload-mix: bad count for {kind!r}: {cnt!r}")
        if n > 0:
            out[kind] = out.get(kind, 0) + n
    if not out:
        raise SystemExit(f"--workload-mix: empty mix {spec!r}")
    return out


async def _kinds_bench(args, model, variables, report):
    """Mixed request-kind workload on ONE paged engine: plain generates,
    n-way forked samples (copy-on-write KV shares), prefill-only
    scores/embeds, and — when ``--constrain-ratio`` > 0 — a slice of the
    generates decoded under a token-mask automaton, all interleaved in
    the same continuous batch. Reports per-kind completion counts and
    latency percentiles plus the two kind-specific costs:
    ``mask_upload_p99_s`` (dirty-mask host→device time, off the decode
    path for every unconstrained slot) and ``fork_overhead_s`` (what an
    n-way sample pays over a plain generate of the same shape — the
    price of the fork, not n prefills). Returns (prompt, tokens) pairs
    for every generate stream AND every fork row so the caller's parity
    cross-check covers both (greedy fork rows must be token-identical
    to generate())."""
    from distkeras_tpu.serving import ServingMetrics
    from distkeras_tpu.serving.metrics import percentile

    mix = _parse_workload_mix(args.workload_mix)
    total = sum(mix.values())
    prompts = _prompts(args, total, salt=303)
    jobs: list[list] = []
    i = 0
    for kind in ("generate", "sample", "score", "embed"):
        for _ in range(mix.get(kind, 0)):
            jobs.append([kind, prompts[i], None])
            i += 1
    # Constrained slice: carve --constrain-ratio of the generates into
    # masked streams driven by a two-state alternating automaton (emit
    # token 1, then 2, repeat) — enough structure that the output
    # PROVES the mask engaged, cheap enough that the cost measured is
    # the mask upload, not the automaton.
    dfa = {"start": 0, "edges": [[0, 1, 1], [1, 2, 0]]}
    gen_jobs = [j for j in jobs if j[0] == "generate"]
    n_con = int(len(gen_jobs) * args.constrain_ratio)
    for j in gen_jobs[:n_con]:
        j[0], j[2] = "constrained", dfa
    rng = np.random.default_rng(args.seed + 31)
    rng.shuffle(jobs)  # interleave: mixed batches are the point

    metrics = ServingMetrics()
    engine = _make_engine(args, model, variables, metrics=metrics,
                          constrained=n_con > 0)
    task = asyncio.create_task(engine.run())
    lats: dict[str, list[float]] = {}
    results: list[tuple[list[int], list[int]]] = []
    errors: list[str] = []
    it = iter(jobs)

    # The validation contract, probed live: a contradictory combo is a
    # TYPED reject at submit (never admitted, never killed mid-stream).
    try:
        engine.submit(prompts[0], max(args.new_tokens, 1), kind="score")
        raise AssertionError(
            "score with max_new_tokens > 0 was admitted — kind "
            "validation must reject contradictory combos at submit")
    except ValueError:
        pass

    async def client():
        for kind, p, constraint in it:
            t0 = time.monotonic()
            try:
                if kind == "sample":
                    req = engine.submit(p, args.new_tokens, kind="sample",
                                        n=args.sample_n)
                    await req.result()
                    rows = req.fork_completions or []
                    if len(rows) != args.sample_n:
                        errors.append(
                            f"sample: {len(rows)} completions != "
                            f"n={args.sample_n}")
                        continue
                    results.extend((p, row) for row in rows)
                elif kind in ("score", "embed"):
                    req = engine.submit(p, 0, kind=kind)
                    await req.result()
                    if kind == "score" and (
                            req.logprobs is None
                            or len(req.logprobs) != len(p) - 1):
                        errors.append("score: logprobs missing/short")
                        continue
                    if kind == "embed" and not req.embedding:
                        errors.append("embed: empty embedding")
                        continue
                elif kind == "constrained":
                    req = engine.submit(p, args.new_tokens,
                                        constraint=constraint)
                    toks = await req.result()
                    want = [1 if t % 2 == 0 else 2
                            for t in range(len(toks))]
                    if toks != want:
                        errors.append(
                            f"constrained: {toks} violates the "
                            f"alternating automaton")
                        continue
                else:
                    req = engine.submit(p, args.new_tokens)
                    results.append((p, await req.result()))
            except Exception as e:  # typed ServingErrors included
                errors.append(f"{kind}: {type(e).__name__}: {e}")
                continue
            lats.setdefault(kind, []).append(time.monotonic() - t0)

    t0 = time.monotonic()
    await asyncio.gather(*(client() for _ in range(args.clients)))
    elapsed = time.monotonic() - t0
    engine.shutdown(drain=True)
    await task

    s = metrics.summary()
    sec: dict = {
        "mix": {k: int(v) for k, v in mix.items()},
        "constrained_requests": n_con,
        "sample_n": args.sample_n if "sample" in mix else None,
        "elapsed_s": round(elapsed, 6),
        "completed": {k: len(v) for k, v in sorted(lats.items())},
        "kind_admitted": metrics.kind_counters(),
        "goodput_tokens_per_sec": round(s["tokens_per_sec"], 3),
    }
    for k, v in sorted(lats.items()):
        sec[f"latency_{k}_p50_s"] = round(percentile(v, 50), 6)
        sec[f"latency_{k}_p99_s"] = round(percentile(v, 99), 6)
    if metrics.fork_blocks:
        sec["fork_blocks_total"] = metrics.fork_blocks
    if lats.get("sample") and lats.get("generate"):
        sec["fork_overhead_s"] = round(
            sum(lats["sample"]) / len(lats["sample"])
            - sum(lats["generate"]) / len(lats["generate"]), 6)
    if s.get("mask_upload_count"):
        sec["mask_upload_count"] = int(s["mask_upload_count"])
        sec["mask_upload_p99_s"] = round(s["mask_upload_p99_s"], 6)
    if errors:
        sec["errors"] = errors
    report["kinds"] = sec

    # The mixed-workload contract, asserted: every request of every
    # kind completed (scorelike traffic never starves decode, forks
    # never leak), and every constrained stream obeyed its automaton.
    assert not errors, f"kind workload failures: {errors}"
    done = dict(sec["completed"])
    want_counts = dict(mix)
    if n_con:
        want_counts["generate"] = want_counts["generate"] - n_con
        want_counts["constrained"] = n_con
    for kind, want in want_counts.items():
        if want:
            assert done.get(kind, 0) == want, (
                f"{kind}: completed {done.get(kind, 0)} of {want}")
    if n_con:
        assert s.get("mask_upload_count"), (
            "constrained streams ran but no mask upload was recorded")
    if "sample" in mix and any(
            len(j[1]) >= args.kv_block for j in jobs if j[0] == "sample"):
        # At least one sample prompt spans a full KV block, so the fork
        # must have handed out copy-on-write shares (lower --kv-block
        # or raise --prompt-len if the mix should exercise this).
        assert metrics.fork_blocks > 0, (
            "block-spanning forks recorded zero CoW shares")
    return results


async def _sweep_point(args, model, variables, slots, salt):
    """One max-concurrent-slots point: a fresh engine at ``slots`` under
    the SAME KV byte budget, saturated closed-loop (>= one client per
    slot), full completion required to count as sustained. Preemptions
    are allowed — they are the oversubscription mechanism — but every
    stream must still finish, token-identical (checked by the caller)."""
    from distkeras_tpu.serving import (
        PoolExhausted, QueueFullError, ServingError,
    )

    engine = _make_engine(args, model, variables, slots=slots)
    prompts = _prompts(args, args.requests, salt=salt)
    task = asyncio.create_task(engine.run())
    results, failures, oom = [], 0, 0
    it = iter(prompts)

    async def client():
        nonlocal failures, oom
        for p in it:
            try:
                req = engine.submit(p, args.new_tokens)
                results.append((p, await req.result()))
            except PoolExhausted:
                oom += 1
            except (QueueFullError, ServingError):
                failures += 1

    t0 = time.monotonic()
    await asyncio.gather(
        *(client() for _ in range(max(args.clients, slots))))
    elapsed = time.monotonic() - t0
    engine.shutdown(drain=True)
    await task
    s = engine.metrics.summary()
    point = {
        "slots": slots,
        "completed": len(results),
        "requests": len(prompts),
        "oom_rejected": oom,
        "failed": failures,
        "kv_preemptions": int(s.get("kv_preemptions", 0)),
        "sustained": (len(results) == len(prompts)
                      and oom == 0 and failures == 0),
        "wall_s": round(elapsed, 3),
        "goodput_tokens_per_sec": round(
            sum(len(t) for _, t in results) / elapsed, 2),
    }
    for key in ("inter_token_p99_s", "inter_token_p50_s", "ttft_p99_s"):
        if key in s:
            point[key] = round(s[key], 6)
    if engine.kv_pool is not None:
        st = engine.kv_pool.stats()
        point["peak_blocks_used"] = st["peak_blocks_used"]
        point["kv_bytes"] = st["capacity_blocks"] * st["bytes_per_block"]
        point["peak_resident_tokens"] = (st["peak_blocks_used"]
                                         * st["block_tokens"])
    return point, results


async def _run_slot_sweep(args, model, variables, report):
    counts = sorted({int(x) for x in args.slot_sweep.split(",") if x})
    points, all_results = [], []
    for i, slots in enumerate(counts):
        point, results = await _sweep_point(args, model, variables, slots,
                                            salt=1000 + i)
        points.append(point)
        all_results.extend(results)
    sustained = [p["slots"] for p in points if p["sustained"]]
    sweep = {
        "kv_pool_mb": args.kv_pool_mb or (8.0 if args.paged else 0.0),
        "paged": bool(args.paged or args.kv_pool_mb > 0),
        "points": points,
        "max_slots_sustained": max(sustained) if sustained else 0,
    }
    best = next((p for p in reversed(points)
                 if p["slots"] == sweep["max_slots_sustained"]), None)
    if best is not None:
        if "inter_token_p99_s" in best:
            sweep["sustained_inter_token_p99_s"] = best["inter_token_p99_s"]
        sweep["sustained_goodput_tokens_per_sec"] = (
            best["goodput_tokens_per_sec"])
        if best.get("kv_bytes") and best.get("peak_resident_tokens"):
            # Tokens-per-byte, the paged headline: resident KV tokens the
            # budget actually carried at peak, per MiB of pool.
            sweep["kv_tokens_per_mib"] = round(
                best["peak_resident_tokens"] / (best["kv_bytes"] / 2**20),
                2)
    report["slot_sweep"] = sweep
    return all_results


# Headline metrics worth a drift gate, per mode section of the report.
# ``spec_accept_rate`` (speculative runs only) is higher-is-better like
# the throughput rows — the regression checker's direction heuristic
# keys off the latency-shaped name prefixes, which it does not match.
_HISTORY_METRICS = (
    "ttft_p50_s", "ttft_p99_s", "inter_token_p50_s", "inter_token_p99_s",
    "prefill_device_p50_s", "goodput_tokens_per_sec", "prefix_hit_rate",
    "spec_accept_rate",
)

# Sweep-level rows: concurrency-at-fixed-bytes and tokens-per-byte (both
# higher-is-better; the p99 ITL at the sustained max is latency-shaped).
_SWEEP_METRICS = (
    "max_slots_sustained", "sustained_inter_token_p99_s",
    "sustained_goodput_tokens_per_sec", "kv_tokens_per_mib",
)


async def _pipeline_ab(args, model, variables, report):
    """Depth-0 vs depth-1 A/B on the same saturated closed-loop
    workload: one fresh engine per depth (pipelining is run-loop
    structure, not compiled state — but a fresh engine keeps the two
    measurements symmetric, warmup included), identical prompts, armed
    auditor both sides, and every stream joins the parity cross-check.
    The depth-1 win is the host gap: goodput up by roughly the depth-0
    ``device_idle_ratio`` (the recorded ``host_gap_fraction``)."""
    from distkeras_tpu.serving import ServingMetrics

    out: dict = {}
    all_results = []
    depth_results: dict[int, list] = {}
    prompts = _prompts(args, args.requests, salt=0)
    for depth in (0, 1):
        engine = _make_engine(args, model, variables,
                              pipeline_depth=depth, arm=True)
        # Warmup pass: pay every prefill-bucket + decode compile OUTSIDE
        # the measured window, then measure on fresh metrics — the A/B's
        # goodput and host-gap fraction must describe the steady state,
        # not one-time compilation (which the gap tracker would honestly
        # book as device idle).
        task = asyncio.create_task(engine.run())
        warm = list(prompts[:min(4, len(prompts))])
        await _closed_loop(engine, warm, args)
        engine.shutdown(drain=True)
        await task
        engine.reopen()
        engine.metrics = ServingMetrics()
        task = asyncio.create_task(engine.run())
        t0 = time.monotonic()
        results = await _closed_loop(engine, list(prompts), args)
        elapsed = time.monotonic() - t0
        engine.shutdown(drain=True)
        await task
        summary = engine.metrics.summary()
        done_tokens = sum(len(t) for _, t in results)
        compiles = engine.decode_compile_count()
        assert compiles in (1, -1), (
            f"pipeline depth {depth} retraced the decode step: "
            f"{compiles} executables")
        out[f"depth{depth}"] = {
            "completed": len(results),
            "wall_s": round(elapsed, 3),
            "goodput_tokens_per_sec": round(done_tokens / elapsed, 2),
            "inter_token_p99_s": round(
                summary.get("inter_token_p99_s", 0.0), 6),
            "ttft_p99_s": round(summary.get("ttft_p99_s", 0.0), 6),
            "host_gap_p50_s": round(summary.get("host_gap_p50_s", 0.0), 9),
            "host_gap_fraction": round(
                summary.get("device_idle_ratio", 0.0), 4),
            "decode_compile_count": compiles,
        }
        all_results.extend(results)
        depth_results[depth] = results
    # THE pipeline invariant, engine-vs-engine: identical prompts must
    # stream identical greedy tokens at both depths (this pair is exempt
    # from the documented slots>1 batch-width tie envelope that can
    # separate EITHER engine from one-shot generate() — same ticks, same
    # order, only the harvest deferred). Buckets come straight from each
    # depth's own result list; a prompt depth 1 never completed counts
    # as a mismatch, not a silent pass.
    per_depth = []
    for depth in (0, 1):
        bucket: dict = {}
        for p, toks in depth_results[depth]:
            bucket.setdefault(tuple(p), toks)
        per_depth.append(bucket)
    depth_mismatches = sum(
        1 for key, toks in per_depth[0].items()
        if per_depth[1].get(key) != toks)
    out["depth_parity_mismatches"] = depth_mismatches
    assert depth_mismatches == 0, (
        f"{depth_mismatches} prompts streamed different tokens at "
        f"depth 1 than depth 0")
    g0 = out["depth0"]["goodput_tokens_per_sec"]
    g1 = out["depth1"]["goodput_tokens_per_sec"]
    if g0 > 0:
        out["speedup_x"] = round(g1 / g0, 3)
    report["pipeline_ab"] = out
    return all_results


def _record_pipeline_history(args, report):
    """``serving/pipeline_*`` rows for the strict CI gate: per-depth
    goodput + saturated p99 ITL, the depth-0 host-gap fraction the
    pipeline exists to hide, the depth-1 residue, and the A/B speedup
    (higher-is-better by name; host_gap* regresses UP)."""
    import os
    import sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    sec = report.get("pipeline_ab") or {}
    path = os.path.join(root, "bench_history.json")
    hist = bench.load_history(path)
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    paged = args.paged or args.kv_pool_mb > 0
    model_tag = f"paged_{args.model}" if paged else args.model
    base = (f"serving/pipeline_{model_tag}/slots{args.slots}"
            f"/clients{args.clients}")
    rows: dict = {"speedup_x": sec.get("speedup_x")}
    for depth in (0, 1):
        d = sec.get(f"depth{depth}") or {}
        rows[f"depth{depth}/goodput_tokens_per_sec"] = (
            d.get("goodput_tokens_per_sec"))
        rows[f"depth{depth}/inter_token_p99_s"] = d.get("inter_token_p99_s")
        rows[f"depth{depth}/host_gap_fraction"] = d.get("host_gap_fraction")
    for metric, v in rows.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
            key = f"{base}/{metric}"
            hist[key] = bench.history_entry(hist.get(key), float(v), when)
    bench.write_history(path, hist)


def _parse_depths(spec: str) -> list[int]:
    """``--pipeline-depths 0,1,2,4`` -> sorted unique non-negative ints
    (typed CLI error on junk, never a deep traceback)."""
    try:
        depths = sorted({int(tok) for tok in spec.split(",") if tok.strip()})
    except ValueError:
        raise SystemExit(f"--pipeline-depths: not an int list: {spec!r}")
    if not depths or any(d < 0 for d in depths):
        raise SystemExit(f"--pipeline-depths: need non-negative depths, "
                         f"got {spec!r}")
    return depths


async def _pp_ab(args, model, variables, report):
    """Depth sweep on a pipeline-parallel mesh: one fresh engine per
    depth in ``--pipeline-depths``, same saturated closed-loop workload,
    armed auditor every time. The pp win is stage overlap: at depth 1 a
    pp=S mesh runs ONE micro-batch, so S-1 stages idle every tick and
    ``bubble_fraction`` sits near 1-1/S; at depth>=S the micro-batched
    ticks keep every stage busy and goodput climbs while the bubble
    collapses. Every depth's streams must be token-identical to every
    other depth's (and, via the caller's parity pass, to generate())."""
    from distkeras_tpu.serving import ServingMetrics

    mesh = _mesh(args)
    pp = dict(mesh.shape).get("pp", 1) if mesh is not None else 1
    if pp <= 1:
        raise SystemExit(
            "--pp-ab needs a pipeline-parallel mesh: pass "
            "--mesh-shape tp=N,pp=M with M>=2 (and --force-host-devices "
            "N*M on a CPU host)")
    depths = _parse_depths(args.pipeline_depths)
    out: dict = {"pp": pp, "depths": depths}
    all_results = []
    depth_results: dict[int, list] = {}
    prompts = _prompts(args, args.requests, salt=0)
    for depth in depths:
        engine = _make_engine(args, model, variables,
                              pipeline_depth=depth, arm=True)
        # Warmup outside the measured window (same discipline as
        # _pipeline_ab): steady-state goodput and bubble, not compiles.
        task = asyncio.create_task(engine.run())
        warm = list(prompts[:min(4, len(prompts))])
        await _closed_loop(engine, warm, args)
        engine.shutdown(drain=True)
        await task
        engine.reopen()
        engine.metrics = ServingMetrics()
        task = asyncio.create_task(engine.run())
        t0 = time.monotonic()
        results = await _closed_loop(engine, list(prompts), args)
        elapsed = time.monotonic() - t0
        engine.shutdown(drain=True)
        await task
        summary = engine.metrics.summary()
        done_tokens = sum(len(t) for _, t in results)
        stage_compiles = engine.decode_compile_counts()
        assert all(c in (1, -1) for c in stage_compiles), (
            f"pp depth {depth} retraced a stage decode step: "
            f"per-stage executables {stage_compiles}")
        bubble = summary.get("bubble_fraction")
        out[f"depth{depth}"] = {
            "completed": len(results),
            "wall_s": round(elapsed, 3),
            "goodput_tokens_per_sec": round(done_tokens / elapsed, 2),
            "inter_token_p99_s": round(
                summary.get("inter_token_p99_s", 0.0), 6),
            "ttft_p99_s": round(summary.get("ttft_p99_s", 0.0), 6),
            "bubble_fraction": (None if bubble is None
                                else round(float(bubble), 4)),
            "stage_compile_counts": stage_compiles,
        }
        all_results.extend(results)
        depth_results[depth] = results
    # Cross-depth parity: identical prompts, identical greedy streams at
    # EVERY depth (micro-batching reorders dispatch, never tokens). A
    # prompt missing at some depth is a mismatch, not a silent pass.
    base_depth = depths[0]
    base_bucket: dict = {}
    for p, toks in depth_results[base_depth]:
        base_bucket.setdefault(tuple(p), toks)
    mismatches = 0
    for depth in depths[1:]:
        bucket: dict = {}
        for p, toks in depth_results[depth]:
            bucket.setdefault(tuple(p), toks)
        mismatches += sum(1 for key, toks in base_bucket.items()
                          if bucket.get(key) != toks)
    out["depth_parity_mismatches"] = mismatches
    assert mismatches == 0, (
        f"{mismatches} prompts streamed different tokens across "
        f"pipeline depths {depths}")
    # Headline: deepest depth vs depth 1 (the tentpole claim — depth>=pp
    # goodput above depth 1 with the bubble reduced).
    if 1 in depths and depths[-1] != 1:
        g1 = out["depth1"]["goodput_tokens_per_sec"]
        gd = out[f"depth{depths[-1]}"]["goodput_tokens_per_sec"]
        if g1 > 0:
            out["speedup_x"] = round(gd / g1, 3)
    report["pp_ab"] = out
    return all_results


def _record_pp_history(args, report):
    """``serving/pp_*`` rows for the strict CI gate: per-depth goodput +
    saturated p99 ITL (higher/lower by name), the measured
    ``bubble_fraction`` each depth leaves on the table (lower-is-better
    — check_bench_regression knows the name), and the deepest-vs-depth-1
    speedup."""
    import os
    import sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    sec = report.get("pp_ab") or {}
    path = os.path.join(root, "bench_history.json")
    hist = bench.load_history(path)
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    paged = args.paged or args.kv_pool_mb > 0
    model_tag = f"paged_{args.model}" if paged else args.model
    base = (f"serving/pp_{model_tag}/pp{sec.get('pp')}/slots{args.slots}"
            f"/clients{args.clients}")
    rows: dict = {"speedup_x": sec.get("speedup_x")}
    for depth in sec.get("depths") or []:
        d = sec.get(f"depth{depth}") or {}
        rows[f"depth{depth}/goodput_tokens_per_sec"] = (
            d.get("goodput_tokens_per_sec"))
        rows[f"depth{depth}/inter_token_p99_s"] = d.get("inter_token_p99_s")
        rows[f"depth{depth}/bubble_fraction"] = d.get("bubble_fraction")
    for metric, v in rows.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
            key = f"{base}/{metric}"
            hist[key] = bench.history_entry(hist.get(key), float(v), when)
    bench.write_history(path, hist)


async def _kv_tier_ab(args, model, variables, report):
    """Pool-only vs tiered A/B on an OVERSUBSCRIBED shared-prefix
    workload: the prefix working set is laid out at ``--kv-tier-oversub``
    times the pool's byte budget (so the pool alone MUST evict every
    family before its revisit), revisited round-robin for
    ``--kv-tier-rounds`` rounds. One fresh armed engine per side with the
    SAME pool config — the only delta is ``--kv-host-tier-mb`` of host
    tier. The tiered win is the re-admit: an evicted family's blocks come
    back over PCIe instead of a recompute prefill, so prefix hit rate AND
    p99 TTFT must both beat the pool-only side, with greedy output
    token-identical between the two."""
    from distkeras_tpu.serving import ServingMetrics

    # Size the workload off the real pool: one probe engine (never run —
    # nothing compiles) tells us blocks-per-prompt and pool capacity.
    probe = _make_engine(args, model, variables)
    pst = probe.kv_pool.stats()
    del probe
    bt = pst["block_tokens"]
    cap_blocks = pst["capacity_blocks"]
    plen = args.prompt_len or max(args.seq_len - args.new_tokens - 1, bt)
    plen = min(plen, args.seq_len - args.new_tokens)
    blocks_per_prompt = max(plen // bt, 1)
    families = max(
        -(-int(args.kv_tier_oversub * cap_blocks) // blocks_per_prompt), 2)
    rng = np.random.default_rng(args.seed)
    prompts = [rng.integers(0, args.vocab, size=plen).tolist()
               for _ in range(families)]
    schedule = prompts * args.kv_tier_rounds
    # Warmup = TWO full rounds of the real schedule: round one overflows
    # the pool (every spill-gather bucket compiles), round two revisits
    # (every re-admit scatter bucket compiles) — so the measured window
    # sees only steady-state executions. Both sides get the same warmup;
    # the pool-only engine just recomputes through it.
    warm = prompts * 2

    out: dict = {
        "block_tokens": bt, "capacity_blocks": cap_blocks,
        "families": families, "rounds": args.kv_tier_rounds,
        "working_set_x_pool": round(
            families * blocks_per_prompt / cap_blocks, 2),
    }
    all_results = []
    side_tokens: dict[str, dict] = {}
    for side, tier_mb in (("pool_only", 0.0),
                          ("tiered", args.kv_host_tier_mb)):
        engine = _make_engine(args, model, variables, arm=True,
                              kv_host_tier_mb=tier_mb)
        # Warmup: pay the prefill-bucket + decode compiles (and the
        # tiered side's gather/scatter staging) outside the measured
        # window, then measure on fresh metrics.
        task = asyncio.create_task(engine.run())
        await _closed_loop(engine, warm, args)
        engine.shutdown(drain=True)
        await task
        engine.reopen()
        engine.metrics = ServingMetrics()
        task = asyncio.create_task(engine.run())
        t0 = time.monotonic()
        results = await _closed_loop(engine, list(schedule), args)
        elapsed = time.monotonic() - t0
        engine.shutdown(drain=True)
        await task
        summary = engine.metrics.summary()
        compiles = engine.decode_compile_count()
        assert compiles in (1, -1), (
            f"kv-tier {side} side retraced the decode step: "
            f"{compiles} executables")
        done_tokens = sum(len(t) for _, t in results)
        out[side] = {
            "completed": len(results),
            "wall_s": round(elapsed, 3),
            "goodput_tokens_per_sec": round(done_tokens / elapsed, 2),
            "ttft_p99_s": round(summary.get("ttft_p99_s", 0.0), 6),
            "prefix_hit_rate": round(
                summary.get("prefix_hit_rate", 0.0), 4),
            "kv_spills": int(summary.get("kv_spills", 0)),
            "kv_spill_bytes": int(summary.get("kv_spill_bytes", 0)),
            "kv_readmits": int(summary.get("kv_readmits", 0)),
            "kv_readmit_bytes": int(summary.get("kv_readmit_bytes", 0)),
            "decode_compile_count": compiles,
        }
        for k in ("kv_spill_latency_p99_s", "kv_readmit_latency_p99_s"):
            if k in summary:
                out[side][k] = round(summary[k], 6)
        if tier_mb:
            out[side]["tier"] = engine.kv_tier.stats()
        bucket: dict = {}
        for p, toks in results:
            bucket.setdefault(tuple(p), toks)
        side_tokens[side] = bucket
        all_results.extend(results)
    # Same prompts, same greedy decode: the tier must be invisible in
    # the tokens — a re-admitted block that decodes differently is a
    # corrupted spill, not a cache win.
    mismatches = sum(
        1 for key, toks in side_tokens["pool_only"].items()
        if side_tokens["tiered"].get(key) != toks)
    out["tier_parity_mismatches"] = mismatches
    assert mismatches == 0, (
        f"{mismatches} prompts streamed different tokens with the host "
        f"tier enabled")
    t_pool = out["pool_only"]["ttft_p99_s"]
    t_tier = out["tiered"]["ttft_p99_s"]
    if t_tier > 0:
        out["ttft_p99_speedup_x"] = round(t_pool / t_tier, 3)
    out["hit_rate_gain"] = round(
        out["tiered"]["prefix_hit_rate"]
        - out["pool_only"]["prefix_hit_rate"], 4)
    report["kv_tier_ab"] = out
    return all_results


async def _kv_tier_push_phase(args, report):
    """Push-vs-pull migration bytes, jax-free: the SAME revisited-family
    workload through a 1 prefill + 1 decode Echo fleet twice — adopt-time
    pulls (every dispatch re-pulls the family's chain), then router push
    scheduling (one push per family; revisits hit the fleet cache
    directory and move nothing). The delta is the bytes the directory
    saves the fabric."""
    from distkeras_tpu.serving import ServingClient, ServingCluster
    from distkeras_tpu.serving.cluster.replicas import EchoReplica
    from distkeras_tpu.telemetry import MetricsRegistry

    bt = args.kv_block
    rng = np.random.default_rng(args.seed + 17)
    prompts = [rng.integers(0, args.vocab, size=2 * bt).tolist()
               for _ in range(4)]
    schedule = prompts * 3
    out: dict = {}
    for mode, push in (("pull", False), ("push", True)):
        registry = MetricsRegistry()
        cluster = ServingCluster(
            lambda i: EchoReplica(kv_block_tokens=bt), 2,
            roles=["prefill", "decode"], registry=registry,
            router_kwargs={"affinity_tokens": bt,
                           "min_handoff_tokens": bt, "kv_push": push})
        pulled = 0
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port,
                                     wire_mode="auto") as c:
                for p in schedule:
                    done = await c.generate(p, 1)
                    assert "error" not in done, done
                    km = done.get("kv_migration") or {}
                    pulled += int(km.get("bytes") or 0)
            # Pushes are scheduled off the dispatch path — drain them
            # before reading the counters.
            deadline = asyncio.get_running_loop().time() + 5.0
            while (cluster.router._push_tasks
                   and asyncio.get_running_loop().time() < deadline):
                await asyncio.sleep(0.02)
            snap = registry.snapshot()
        rec = {"requests": len(schedule), "pulled_bytes": pulled}
        if push:
            rec.update({
                "pushes": int(
                    snap["router_kv_pushes_total"]["value"]),
                "pushed_bytes": int(
                    snap["router_kv_push_bytes_total"]["value"]),
                "push_fallbacks": int(
                    snap["router_kv_push_fallbacks_total"]["value"]),
                "directory_hits": int(
                    snap["router_kv_directory_hits_total"]["value"]),
                "directory_bytes_saved": int(
                    snap["router_kv_push_bytes_saved_total"]["value"]),
            })
        out[mode] = rec
    moved_pull = out["pull"]["pulled_bytes"]
    moved_push = out["push"]["pushed_bytes"] + out["push"]["pulled_bytes"]
    out["migration_bytes_saved"] = moved_pull - moved_push
    report["kv_tier_push_vs_pull"] = out


def _record_kvtier_history(args, report):
    """``serving/kvtier_*`` rows for the strict CI gate: per-side prefix
    hit rate + p99 TTFT (the tiered side must beat pool-only on BOTH),
    the tiered side's spill/readmit traffic and latency tails
    (``*_latency_*`` regresses UP), and the push-vs-pull bytes the fleet
    directory saves."""
    import os
    import sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    sec = report.get("kv_tier_ab") or {}
    push = report.get("kv_tier_push_vs_pull") or {}
    path = os.path.join(root, "bench_history.json")
    hist = bench.load_history(path)
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    base = (f"serving/kvtier_{args.model}/slots{args.slots}"
            f"/clients{args.clients}")
    rows: dict = {
        "ttft_p99_speedup_x": sec.get("ttft_p99_speedup_x"),
        "hit_rate_gain": sec.get("hit_rate_gain"),
        "migration_bytes_saved": push.get("migration_bytes_saved"),
        "directory_bytes_saved": (push.get("push") or {}).get(
            "directory_bytes_saved"),
    }
    for side in ("pool_only", "tiered"):
        d = sec.get(side) or {}
        rows[f"{side}/prefix_hit_rate"] = d.get("prefix_hit_rate")
        rows[f"{side}/ttft_p99_s"] = d.get("ttft_p99_s")
        rows[f"{side}/goodput_tokens_per_sec"] = d.get(
            "goodput_tokens_per_sec")
    d = sec.get("tiered") or {}
    rows["tiered/spill_bytes"] = d.get("kv_spill_bytes")
    rows["tiered/readmit_bytes"] = d.get("kv_readmit_bytes")
    rows["tiered/spill_latency_p99_s"] = d.get("kv_spill_latency_p99_s")
    rows["tiered/readmit_latency_p99_s"] = d.get(
        "kv_readmit_latency_p99_s")
    for metric, v in rows.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
            key = f"{base}/{metric}"
            hist[key] = bench.history_entry(hist.get(key), float(v), when)
    bench.write_history(path, hist)


async def _slo_bench(args, report):
    """Fleet telemetry + SLO acceptance: three phases on one fleet size.

    1. **baseline** — the same N-replica cluster with the push plane
       off (``telemetry_interval_s=0``): its goodput is the no-push
       reference the push phase's overhead is measured against.
    2. **push** — plane on at ``--slo-push-interval``. After the load,
       the router's fleet-merged TTFT/ITL p99 (pushed deltas, folded
       bucket-exact) is diffed against an offline percentile over the
       POOLED RAW samples read straight off every replica engine's
       sample deques — the mergeable-histogram exactness contract,
       end to end. Tolerance: one bucket width at the offline p99
       (inside a bucket the merged estimate interpolates; across
       replicas the bucket counts are exact).
    3. **breach** — ``inject_latency`` (the server's chaos verb, sent
       replica-direct: the router doesn't forward unknown verbs) slows
       every decode tick past the ITL objective's snapped bound. The
       burn engine must take the fleet ``ok -> page`` with >=1 exemplar
       trace id on the breach transitions, and recover to ``ok`` once
       the injection is cleared and the windows drain.

    Every replica arms the :class:`RecompileAuditor`: the telemetry
    plane must add ZERO retraces (decode compile count stays 1).
    """
    import bisect
    import time as _time

    from distkeras_tpu.serving import (
        LocalReplica, ServingClient, ServingCluster,
    )
    from distkeras_tpu.serving.cluster.replicas import send_control
    from distkeras_tpu.serving.metrics import _LATENCY_BUCKETS, percentile
    from distkeras_tpu.serving.slo import default_objectives
    from distkeras_tpu.telemetry import MetricsRegistry
    from distkeras_tpu.telemetry.registry import hist_state_percentile

    model, variables = _model(args)

    def replica(i):
        return LocalReplica(
            lambda: _make_engine(args, model, variables, arm=True))

    async def drive(port, n, salt, new_tokens=None):
        """One closed-loop round; returns (wall_s, done_tokens)."""
        prompts = _prompts(args, n, salt)
        it = iter(prompts)
        tokens = 0

        async def client():
            nonlocal tokens
            async with ServingClient("127.0.0.1", port) as c:
                for p in it:
                    done = await c.generate(
                        p, new_tokens or args.new_tokens)
                    tokens += len(done["tokens"])

        t0 = _time.monotonic()
        await asyncio.gather(*(client() for _ in range(args.clients)))
        return _time.monotonic() - t0, tokens

    sup = dict(health_interval_s=0.1, base_delay_s=0.2)
    sec: dict = {}
    report["slo_bench"] = sec

    # Phase 1: no-push baseline — a fresh fleet, the push plane's kill
    # switch thrown, the SAME prompts the push phase will replay.
    cluster = ServingCluster(
        replica, args.replicas, registry=MetricsRegistry(),
        router_kwargs={"telemetry_interval_s": 0.0},
        supervisor_kwargs=sup)
    async with cluster:
        wall, tokens = await drive(cluster.port, args.requests, 0)
    sec["baseline"] = {
        "wall_s": round(wall, 3),
        "goodput_tokens_per_sec": round(tokens / wall, 2),
    }

    # Phases 2 + 3 share one fleet with the plane on. Windows are
    # bench-scaled (seconds, not SRE minutes/hours) so the breach pages
    # — and recovery drains — inside a CPU demo run's patience.
    slow_window_s = 4.0
    cluster = ServingCluster(
        replica, args.replicas, registry=MetricsRegistry(),
        router_kwargs={
            "telemetry_interval_s": args.slo_push_interval,
            "slo_objectives": default_objectives(
                ttft_threshold_s=args.slo_ttft_threshold,
                itl_threshold_s=args.slo_itl_threshold),
            "slo_kwargs": {"fast_window_s": 1.0,
                           "slow_window_s": slow_window_s},
        },
        supervisor_kwargs=sup)
    async with cluster:
        router = cluster.router
        wall, tokens = await drive(cluster.port, args.requests, 0)
        goodput = tokens / wall
        base_gp = sec["baseline"]["goodput_tokens_per_sec"]
        sec["push"] = {
            "wall_s": round(wall, 3),
            "goodput_tokens_per_sec": round(goodput, 2),
            # Clamped at 0: CPU A/B noise routinely makes the push side
            # FASTER, and a negative overhead row would train the drift
            # gate on noise.
            "push_overhead_pct": round(
                max(0.0, (base_gp - goodput) / base_gp * 100.0), 3),
        }

        engines = [info.handle.engine
                   for info in cluster.replicas.values()
                   if getattr(info.handle, "engine", None) is not None]

        async def settled(name, n_raw):
            # Wait until every raw sample has been pushed and folded
            # (the plane is asynchronous; counts converge within a few
            # cadences once the load stops).
            deadline = _time.monotonic() + 10.0
            st = None
            while _time.monotonic() < deadline:
                st = router.fleet.fleet_hist_state(name)
                if st is not None and st.get("count", 0) >= n_raw:
                    break
                await asyncio.sleep(args.slo_push_interval)
            return st

        agg: dict = {}
        for label, metric, attr in (
                ("ttft", "serving_ttft_seconds", "ttft"),
                ("itl", "serving_inter_token_seconds", "inter_token")):
            xs = [float(x) for eng in engines
                  for x in getattr(eng.metrics, attr)]
            st = await settled(metric, len(xs))
            assert st is not None and xs, f"no fleet samples for {metric}"
            fleet_p99 = hist_state_percentile(st, 99)
            off_p99 = percentile(xs, 99)
            bounds = list(_LATENCY_BUCKETS)
            bi = bisect.bisect_left(bounds, off_p99)
            lo = bounds[bi - 1] if bi > 0 else 0.0
            hi = bounds[bi] if bi < len(bounds) else 2 * bounds[-1]
            err = abs(fleet_p99 - off_p99)
            agg[label] = {
                "fleet_p99_s": round(fleet_p99, 6),
                "offline_p99_s": round(off_p99, 6),
                "abs_err_s": round(err, 6),
                "bucket_width_s": round(hi - lo, 6),
                "samples": len(xs),
                "merged_count": int(st.get("count", 0)),
            }
            assert err <= (hi - lo) + 1e-9, (
                f"fleet-merged {label} p99 {fleet_p99:.6f}s is more "
                f"than one bucket width ({hi - lo:.6f}s) from the "
                f"offline recompute {off_p99:.6f}s over {len(xs)} raw "
                f"samples")
        stats = router.telemetry_stats()
        agg["staleness_s"] = stats.get("staleness_s")
        agg["pushes"] = stats.get("pushes")
        agg["push_errors"] = stats.get("push_errors")
        agg["push_subscriptions"] = stats.get("push_subscriptions")
        sec["aggregation"] = agg

        async with ServingClient("127.0.0.1", cluster.port) as ctl:
            async def sloz():
                rep = await ctl._control({"cmd": "sloz"})
                return rep["sloz"]

            async def poll_until(state, timeout):
                deadline = _time.monotonic() + timeout
                while _time.monotonic() < deadline:
                    snap = await sloz()
                    if snap["overall"] == state:
                        return snap
                    await asyncio.sleep(0.25)
                return None

            # Let any load-phase burn (e.g. first-request prefill
            # compiles tripping the ITL objective) drain out of the
            # windows: the ok -> page transition below must be OURS.
            snap = await poll_until("ok", 3 * slow_window_s + 10.0)
            assert snap is not None, (
                f"fleet never settled to ok before the breach: "
                f"{await sloz()}")

            # Phase 3: the controlled breach.
            for info in cluster.replicas.values():
                await send_control(
                    "127.0.0.1", info.port,
                    {"cmd": "inject_latency",
                     "decode_delay_s": args.slo_inject_delay})
            t_inject = _time.monotonic()
            load = asyncio.create_task(drive(
                cluster.port, 2 * args.clients, 1,
                new_tokens=args.slo_breach_tokens))
            try:
                paged = await poll_until(
                    "page", 6 * slow_window_s + 30.0)
            finally:
                await load
            assert paged is not None, (
                "injected latency never drove the fleet to page")
            time_to_page = _time.monotonic() - t_inject
            breaches = [e for e in paged["events"]
                        if e["to"] in ("warn", "page")]
            exemplars = sorted({x for e in breaches
                                for x in e.get("exemplars") or ()})
            assert exemplars, (
                f"no exemplar trace ids on the breach transitions: "
                f"{breaches}")

            # Clear the injection; the windows must drain back to ok.
            for info in cluster.replicas.values():
                await send_control("127.0.0.1", info.port,
                                   {"cmd": "inject_latency",
                                    "decode_delay_s": 0.0})
            recovered = await poll_until("ok", 6 * slow_window_s + 30.0)
            assert recovered is not None, (
                "fleet never recovered to ok after the injection was "
                "cleared")
            final = await sloz()
            sec["breach"] = {
                "inject_delay_s": args.slo_inject_delay,
                "time_to_page_s": round(time_to_page, 3),
                "exemplars": exemplars[:8],
                "transitions": [
                    {k: e[k] for k in ("objective", "from", "to")}
                    for e in final["events"]],
                "recovered": True,
            }
            evals = max(1, final["evaluations"])
            sec["burn_engine"] = {
                "evaluations": final["evaluations"],
                "eval_cost_s": final["eval_cost_s"],
                "burn_overhead_per_eval_s": round(
                    final["eval_cost_s"] / evals, 9),
            }

        # The standing invariant: the telemetry plane added no retraces.
        compiles = {
            rid: info.handle.engine.decode_compile_count()
            for rid, info in cluster.replicas.items()
            if info.handle.engine is not None
        }
        sec["decode_compile_count"] = compiles
        assert all(c in (1, -1, 0) for c in compiles.values()), compiles


def _record_slo_history(args, report):
    """``serving/slo_*`` rows for the strict CI gate: push overhead and
    aggregation staleness (both regress UP), the fleet-merged latency
    percentiles and their offline-recompute error (UP), the burn
    engine's per-evaluation cost and time-to-page (UP), and the push
    phase's goodput (DOWN)."""
    import os
    import sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    sec = report.get("slo_bench") or {}
    push = sec.get("push") or {}
    agg = sec.get("aggregation") or {}
    path = os.path.join(root, "bench_history.json")
    hist = bench.load_history(path)
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    base = (f"serving/slo_{args.model}/replicas{args.replicas}"
            f"/slots{args.slots}")
    rows = {
        "goodput_tokens_per_sec": push.get("goodput_tokens_per_sec"),
        "push_overhead_pct": push.get("push_overhead_pct"),
        "staleness_s": agg.get("staleness_s"),
        "time_to_page_s": (sec.get("breach") or {}).get("time_to_page_s"),
        "burn_overhead_per_eval_s": (sec.get("burn_engine") or {}).get(
            "burn_overhead_per_eval_s"),
    }
    for label in ("ttft", "itl"):
        d = agg.get(label) or {}
        rows[f"{label}_p99_fleet_s"] = d.get("fleet_p99_s")
        rows[f"{label}_p99_abs_err_s"] = d.get("abs_err_s")
    for metric, v in rows.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
            key = f"{base}/{metric}"
            hist[key] = bench.history_entry(hist.get(key), float(v), when)
    bench.write_history(path, hist)


def _queryz_probe(args, engine, report):
    """Measure the wide-event plane's two costs on the LIVE engine after
    the load phases:

    1. **append overhead** — the store self-times every append with one
       ``perf_counter_ns`` pair, so total append ns over the serving
       wall clock is the plane's real done-time tax. Asserted < 1%:
       wide events must stay effectively free next to decode.
    2. **query latency** — the ring is padded to CAPACITY with synthetic
       rows (the worst-case full scan) and a representative two-column
       group-by with count/p99/mean aggregates is timed repeatedly; the
       median is the number an operator's queryz actually costs a live
       engine.

    Runs after the compile-count assertion, so the probe also certifies
    that emission added no retrace."""
    import statistics

    store = engine.wide_events
    stats = store.stats()
    wall = sum((report.get(m) or {}).get("wall_s", 0.0)
               for m in ("closed", "open"))
    overhead_pct = (100.0 * stats["append_ns_total"] / (wall * 1e9)
                    if wall > 0 else None)
    rows_from_run = stats["rows"]
    i = 0
    while len(store) < store.capacity:
        store.append({"trace_id": f"pad{i}", "tenant": f"t{i % 8}",
                      "kind": "sample", "status": "ok",
                      "ttft_s": 0.001 * (i % 97 + 1),
                      "latency_s": 0.01 * (i % 53 + 1)})
        i += 1
    lat = []
    res = None
    for _ in range(15):
        t0 = time.perf_counter()
        res = store.query(group_by=["tenant", "kind"],
                          aggs=["count", "p99:ttft_s", "mean:latency_s"])
        lat.append(time.perf_counter() - t0)
    report["queryz_probe"] = {
        "rows_from_run": rows_from_run,
        "rows_padded_to": len(store),
        "append_ns_mean": round(stats["append_ns_mean"], 1),
        "append_overhead_pct": (round(overhead_pct, 5)
                                if overhead_pct is not None else None),
        "query_groups": len(res["groups"]),
        "query_latency_p50_s": round(statistics.median(lat), 6),
    }
    if overhead_pct is not None:
        assert overhead_pct < 1.0, (
            f"wide-event append cost {overhead_pct:.3f}% of serving "
            f"wall — the done-time plane must stay under 1%")


def _record_history(args, report):
    """Append this run's headline numbers to ``bench_history.json`` under
    ``serving/...`` keys, via ``bench.py``'s shared ``history_entry`` /
    ``write_history`` helpers — training and serving rows keep ONE entry
    shape for ``scripts/check_bench_regression.py`` to diff. Latency
    metrics are named so the checker knows lower-is-better."""
    import os
    import sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench  # stdlib-only parent module

    path = os.path.join(root, "bench_history.json")
    hist = bench.load_history(path)
    paged = args.paged or args.kv_pool_mb > 0
    model_tag = f"paged_{args.model}" if paged else args.model
    if args.mesh or args.mesh_shape:
        # serving/sharded_* rows: the GSPMD tensor-parallel engine's
        # numbers diff against their own prior, never the single-device
        # series — and ride the same strict --only serving/ CI gate.
        tp = dict(getattr(args, "_mesh").shape).get("tp", 0)
        model_tag = f"sharded_{model_tag}_tp{tp}"
    if _speculating(args):
        # serving/spec_* rows: accept rate, goodput, ITL percentiles of
        # speculative runs diff against their own prior — never against
        # the one-token baseline series.
        model_tag = f"spec_{model_tag}"
    base = f"serving/{model_tag}/slots{args.slots}"
    if _speculating(args):
        base += f"/k{args.spec_k}"
        if args.draft_model and args.draft_model != args.model:
            base += f"/draft_{args.draft_model}"
    if paged:
        base += (f"/pool{args.kv_pool_mb or 8:g}mb"
                 f"/block{args.kv_block}")
    if args.prefix_ratio > 0:
        base += f"/prefix{args.prefix_ratio:g}x{args.prefix_count}"
    if args.prefix_cache_mb > 0 and not paged:
        base += f"/cache{args.prefix_cache_mb:g}mb"
    if args.prefill_chunk:
        base += f"/chunk{args.prefill_chunk}"
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    for mode in ("closed", "open"):
        sec = report.get(mode)
        if not isinstance(sec, dict):
            continue
        from scripts.check_bench_regression import lower_is_better

        for metric in _HISTORY_METRICS:
            v = sec.get(metric)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            if v <= 0 and lower_is_better(metric):
                # A zero LATENCY headline (speculative ITL p50 is
                # exactly 0.0 — tokens of one tick share a timestamp)
                # is degenerate and can never serve as a prior for the
                # drift gate (check_bench_regression skips zero
                # priors), so recording it would only LOOK gated. A
                # zero throughput/accept-rate value is the opposite: a
                # collapse the gate MUST see against its positive
                # prior — never drop those.
                continue
            key = f"{base}/{mode}/{metric}"
            hist[key] = bench.history_entry(hist.get(key), float(v), when)
    sweep = report.get("slot_sweep")
    if isinstance(sweep, dict):
        for metric in _SWEEP_METRICS:
            v = sweep.get(metric)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            key = f"{base}/sweep/{metric}"
            hist[key] = bench.history_entry(hist.get(key), float(v), when)
    probe = report.get("queryz_probe")
    if isinstance(probe, dict):
        # serving/widevents_* rows: the wide-event plane's own series
        # (append tax, full-ring query latency), lower-is-better by
        # name, same strict --only serving/ CI gate as everything else.
        wbase = f"serving/widevents_{model_tag}/slots{args.slots}"
        for metric in ("append_overhead_pct", "append_ns_mean",
                       "query_latency_p50_s"):
            v = probe.get(metric)
            if (isinstance(v, (int, float)) and not isinstance(v, bool)
                    and v > 0):
                key = f"{wbase}/{metric}"
                hist[key] = bench.history_entry(hist.get(key), float(v),
                                                when)
    bench.write_history(path, hist)


def _parse_roles_spec(spec: str) -> list[str]:
    """``prefill=N,decode=M`` via the ONE shared parser (bad input is a
    typed CLI exit)."""
    from distkeras_tpu.serving.cluster import parse_roles

    try:
        return parse_roles(spec)
    except ValueError as e:
        raise SystemExit(f"--roles: {e}") from None


def _record_disagg_history(args, report, roles):
    """``serving/disagg_*`` rows for the strict CI gate: saturated-fleet
    client-observed p99 TTFT/ITL (lower-is-better by name), the
    migration/fallback/bytes counters, and — when a monolithic baseline
    ran — the p99-ITL improvement factor (higher-is-better)."""
    import os
    import sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    path = os.path.join(root, "bench_history.json")
    hist = bench.load_history(path)
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    base = (f"serving/disagg_{args.model}"
            f"/p{roles.count('prefill')}d{roles.count('decode')}"
            f"/slots{args.slots}/block{args.kv_block}")
    disagg = report.get("disagg") or {}
    for mode in ("closed", "open"):
        sec = report.get(mode)
        if not isinstance(sec, dict):
            continue
        rows = {
            "ttft_p99_s": sec.get("ttft_p99_s"),
            "itl_p99_s": sec.get("itl_p99_s"),
            "goodput_tokens_per_sec": sec.get("goodput_tokens_per_sec"),
            "speedup_itl_x": sec.get("speedup_itl_x"),
        }
        for metric, v in rows.items():
            if isinstance(v, (int, float)) and v > 0:
                key = f"{base}/{mode}/{metric}"
                hist[key] = bench.history_entry(hist.get(key), float(v),
                                                when)
    for metric in ("migrations", "fallbacks", "bytes_moved"):
        v = disagg.get(metric)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            key = f"{base}/{metric}"
            hist[key] = bench.history_entry(hist.get(key), float(v),
                                            when)
    bench.write_history(path, hist)


def _record_qos_history(args, report):
    """``serving/qos_*`` rows for the strict CI gate: the others' p99
    TTFT under flood and the flood/baseline degradation ratio — both
    ttft-named, so the checker knows lower-is-better."""
    import os
    import sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    sec = report.get("qos") or {}
    path = os.path.join(root, "bench_history.json")
    hist = bench.load_history(path)
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    base = (f"serving/qos_{args.model}/tenants{args.tenants}"
            f"/hot{sec.get('hot_qps', 0):g}")
    rows = {
        "ttft_p99_others_flood_s":
            (sec.get("flood") or {}).get("ttft_p99_others_s"),
        "ttft_p99_others_baseline_s":
            (sec.get("baseline") or {}).get("ttft_p99_others_s"),
        "ttft_degradation_ratio": sec.get("ttft_degradation_ratio"),
    }
    for metric, v in rows.items():
        if isinstance(v, (int, float)) and v > 0:
            key = f"{base}/{metric}"
            hist[key] = bench.history_entry(hist.get(key), float(v), when)
    bench.write_history(path, hist)


def _record_kinds_history(args, report):
    """``serving/kinds_*`` rows for the strict CI gate: per-kind p99
    latency (latency-named → lower-is-better), mixed-workload goodput,
    and the two kind-specific costs the checker learns by prefix —
    ``mask_upload`` (dirty-mask host→device time) and ``fork_overhead``
    (what an n-way sample pays over a plain generate)."""
    import os
    import sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    sec = report.get("kinds") or {}
    path = os.path.join(root, "bench_history.json")
    hist = bench.load_history(path)
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    mixtag = ",".join(f"{k}{v}" for k, v in sorted(
        (sec.get("mix") or {}).items()))
    base = (f"serving/kinds_{args.model}/{mixtag}"
            f"/n{sec.get('sample_n') or 1}")
    rows = {
        "goodput_tokens_per_sec": sec.get("goodput_tokens_per_sec"),
        "mask_upload_p99_s": sec.get("mask_upload_p99_s"),
        "fork_overhead_s": sec.get("fork_overhead_s"),
    }
    for kind in ("generate", "constrained", "sample", "score", "embed"):
        rows[f"latency_{kind}_p99_s"] = sec.get(f"latency_{kind}_p99_s")
    for metric, v in rows.items():
        if isinstance(v, (int, float)) and v > 0:
            key = f"{base}/{metric}"
            hist[key] = bench.history_entry(hist.get(key), float(v), when)
    bench.write_history(path, hist)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="both",
                    choices=["closed", "open", "both"])
    ap.add_argument("--model", default="gpt_tiny",
                    choices=["gpt_tiny", "gpt_small"])
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=16)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--clients", type=int, default=6,
                    help="closed-loop concurrency")
    ap.add_argument("--rate", type=float, default=30.0,
                    help="open-loop offered load, req/s")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--prompt-len", type=int, default=None,
                    help="fixed prompt length (default: small mixed pool; "
                         "required basis for the shared-prefix workload)")
    ap.add_argument("--prefix-ratio", type=float, default=0.0,
                    help="> 0: shared-prefix workload — this share of "
                         "every prompt comes from a shared system prompt")
    ap.add_argument("--prefix-count", type=int, default=1,
                    help="distinct shared prefixes in the workload")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="engine chunked-prefill size (tokens)")
    ap.add_argument("--prefix-cache-mb", type=float, default=0.0,
                    help="engine prefix-cache byte budget (MB); 0 = off")
    ap.add_argument("--prefix-block", type=int, default=16,
                    help="prefix-cache block granularity (tokens)")
    ap.add_argument("--paged", action="store_true",
                    help="paged KV: slots allocate blocks from one "
                         "shared pool (which doubles as the prefix "
                         "cache); default 8 MB budget unless "
                         "--kv-pool-mb is given")
    ap.add_argument("--kv-pool-mb", type=float, default=0.0,
                    help="paged-KV pool byte budget (MB); > 0 implies "
                         "--paged")
    ap.add_argument("--kv-block", type=int, default=16,
                    help="paged-KV block granularity (tokens)")
    ap.add_argument("--max-context", type=int, default=None,
                    help="per-request context cap; in DENSE mode also "
                         "the pre-reserved per-slot cache length — the "
                         "knob that fixes the dense side of a "
                         "slots-at-fixed-bytes comparison")
    ap.add_argument("--speculate", action="store_true",
                    help="speculative decoding: a draft model proposes "
                         "--spec-k tokens per tick, ONE batched target "
                         "call verifies. Default draft is the target "
                         "itself (the sanity config: ~100%% acceptance, "
                         "speedup = pure dispatch amortization); the "
                         "armed auditor asserts draft/verify/fallback "
                         "each compile exactly once")
    ap.add_argument("--draft-model", default=None,
                    choices=["gpt_tiny", "gpt_small"],
                    help="draft model (implies --speculate; default: "
                         "same as --model)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens per speculative tick")
    ap.add_argument("--mesh", action="store_true",
                    help="GSPMD tensor-parallel engine: shard params + "
                         "KV over every visible device's tp axis; arms "
                         "the auditor and asserts token-identical "
                         "greedy parity vs the unsharded generate() "
                         "reference")
    ap.add_argument("--mesh-shape", default=None, metavar="AXIS=N[,..]",
                    help="explicit serving mesh shape (implies --mesh), "
                         "e.g. 'tp=2'")
    ap.add_argument("--force-host-devices", type=int, default=None,
                    metavar="N",
                    help="force N virtual CPU devices (set before jax "
                         "loads) — how a CPU host runs --mesh")
    ap.add_argument("--slot-sweep", default=None, metavar="N1,N2,...",
                    help="max-concurrent-slots-at-fixed-bytes sweep: "
                         "re-run the closed-loop phase at each slot "
                         "count with the SAME KV byte budget and report "
                         "which counts sustain full completion (paged: "
                         "pool budget fixed; dense: per-slot cache ~ "
                         "--max-context) plus saturated p99 ITL per "
                         "point")
    ap.add_argument("--replicas", type=int, default=0,
                    help=">= 2: drive an in-process cluster (N engines "
                         "behind the supervised router) over TCP instead "
                         "of one engine directly")
    ap.add_argument("--roles", default=None, metavar="prefill=N,decode=M",
                    help="disaggregated fleet mode (implies cluster + "
                         "--paged): N prefill replicas prefill and "
                         "export KV blocks, M decode replicas adopt "
                         "them and stream — the report carries "
                         "client-observed p99 TTFT/ITL plus the "
                         "fleet's migration/fallback/bytes counters")
    ap.add_argument("--disagg-baseline", action="store_true",
                    help="roles mode: first run the SAME workload on a "
                         "monolithic fleet of equal size, and report "
                         "the p99 ITL improvement disaggregation buys "
                         "(speedup_itl_x)")
    ap.add_argument("--min-itl-improvement", type=float, default=0.0,
                    help="roles mode with --disagg-baseline: assert the "
                         "closed-phase p99-ITL improvement is at least "
                         "this factor; 0 = report only")
    ap.add_argument("--chaos-kill-at", type=float, default=None,
                    help="cluster mode: hard-kill replica r0 this many "
                         "seconds into each load phase and assert the "
                         "retry/restart contract")
    ap.add_argument("--tenants", type=int, default=0,
                    help=">= 2: the ADVERSARIAL multi-tenant workload — "
                         "N tenants share the engine, tenant t0 floods "
                         "at --hot-tenant-qps while the others offer "
                         "their fair share of --rate; per-tenant quotas "
                         "+ DRR fair queueing must shed the flood as "
                         "typed rejects without moving the others' p99 "
                         "TTFT")
    ap.add_argument("--hot-tenant-qps", type=float, default=None,
                    help="flood phase offered rate for tenant t0 "
                         "(default: 10x its fair share of --rate)")
    ap.add_argument("--tenant-quota", action="append", default=None,
                    metavar="TENANT=TOK_S",
                    help="per-tenant token-rate quota (repeatable); "
                         "default in --tenants mode: 2x each tenant's "
                         "fair-share token rate with a 4s burst bucket "
                         "(honest Poisson bursts clear it, a 10x flood "
                         "does not)")
    ap.add_argument("--qos-max-degradation", type=float, default=0.0,
                    help="assert the others' flood/baseline p99-TTFT "
                         "ratio stays <= this (acceptance: 1.25); 0 = "
                         "report only")
    ap.add_argument("--pipeline-depth", type=int, default=1,
                    help="decode pipeline depth: 1 (default) dispatches "
                         "tick N+1 before consuming tick N's tokens so "
                         "host bookkeeping hides behind device compute; "
                         ">=2 on a pp mesh micro-batches the slots so "
                         "every stage stays busy; 0 serializes "
                         "dispatch+harvest")
    ap.add_argument("--pipeline-depths", default="0,1,2,4",
                    metavar="D1,D2,...",
                    help="--pp-ab: comma-separated pipeline depths to "
                         "sweep (default 0,1,2,4)")
    ap.add_argument("--pp-ab", action="store_true",
                    help="pipeline-parallel depth sweep: run the "
                         "closed-loop workload at every --pipeline-depths "
                         "depth on the --mesh-shape tp=N,pp=M mesh (fresh "
                         "armed engine each), report per-depth goodput / "
                         "p99 ITL / bubble_fraction + the deepest-vs-"
                         "depth-1 speedup, cross-check every depth's "
                         "streams token-identical, and record "
                         "serving/pp_* history rows")
    ap.add_argument("--pipeline-ab", action="store_true",
                    help="A/B the decode pipeline: run the closed-loop "
                         "workload at depth 0 then depth 1 (fresh armed "
                         "engine each), report per-depth goodput / p99 "
                         "ITL / host-gap fraction and the speedup, and "
                         "join every stream into the parity cross-check; "
                         "records serving/pipeline_* history rows")
    ap.add_argument("--min-speedup", type=float, default=0.0,
                    help="--pipeline-ab: assert depth-1 goodput is at "
                         "least this factor of depth-0 (acceptance: "
                         "strictly above 1.0); 0 = report only")
    ap.add_argument("--kv-tier", action="store_true",
                    help="tiered-KV A/B: run an oversubscribed "
                         "shared-prefix workload (working set "
                         "--kv-tier-oversub x the pool bytes, revisited "
                         "for --kv-tier-rounds rounds) on a pool-only "
                         "engine then the SAME pool + --kv-host-tier-mb "
                         "of host tier; report per-side prefix hit rate "
                         "/ p99 TTFT / spill+readmit traffic, assert "
                         "token parity between the sides, then measure "
                         "push-vs-pull migration bytes on an Echo "
                         "fleet; records serving/kvtier_* history rows")
    ap.add_argument("--kv-host-tier-mb", type=float, default=8.0,
                    help="--kv-tier: host-RAM tier byte budget (MB) for "
                         "the tiered side")
    ap.add_argument("--kv-tier-oversub", type=float, default=10.0,
                    help="--kv-tier: prefix working set as a multiple "
                         "of the pool's byte budget")
    ap.add_argument("--kv-tier-rounds", type=int, default=3,
                    help="--kv-tier: times each prefix family is "
                         "revisited")
    ap.add_argument("--kv-tier-strict", action="store_true",
                    help="--kv-tier: assert the tiered side beats "
                         "pool-only on BOTH prefix hit rate and p99 "
                         "TTFT (the acceptance gate); default is "
                         "report-only")
    ap.add_argument("--slo", action="store_true",
                    help="fleet telemetry + SLO acceptance: a no-push "
                         "baseline fleet vs the same fleet with the "
                         "telemetry push plane on (goodput overhead), "
                         "fleet-merged TTFT/ITL p99 checked against an "
                         "offline recompute from every replica's raw "
                         "samples (within one bucket width), then an "
                         "injected-latency breach that must take the "
                         "burn engine ok -> page with exemplar trace "
                         "ids and recover; records serving/slo_* rows")
    ap.add_argument("--slo-push-interval", type=float, default=0.1,
                    help="--slo: replica->router telemetry push cadence "
                         "(seconds)")
    ap.add_argument("--slo-ttft-threshold", type=float, default=30.0,
                    help="--slo: TTFT objective threshold (seconds; "
                         "generous by default so a CPU fleet's healthy "
                         "phase stays ok)")
    ap.add_argument("--slo-itl-threshold", type=float, default=2.0,
                    help="--slo: inter-token objective threshold "
                         "(seconds; the breach objective — "
                         "--slo-inject-delay must exceed its snapped "
                         "bucket bound)")
    ap.add_argument("--slo-inject-delay", type=float, default=3.0,
                    help="--slo: per-decode-tick delay (seconds) the "
                         "breach phase injects on every replica via the "
                         "inject_latency chaos verb")
    ap.add_argument("--slo-breach-tokens", type=int, default=4,
                    help="--slo: tokens per breach-phase request (small: "
                         "each decode tick costs --slo-inject-delay)")
    ap.add_argument("--slo-strict", action="store_true",
                    help="--slo: assert telemetry push overhead <= 2%% "
                         "of baseline goodput (CPU A/B goodput is "
                         "noisy; default is report-only)")
    ap.add_argument("--workload-mix", default=None,
                    metavar="generate:N,sample:M,score:K[,embed:J]",
                    help="mixed request-kind mode (implies --paged): "
                         "run the given per-kind request counts "
                         "interleaved on ONE engine — plain generates, "
                         "n-way forked samples (CoW KV shares), "
                         "prefill-only scores/embeds, plus a "
                         "--constrain-ratio slice of the generates "
                         "decoded under a token-mask automaton; "
                         "reports per-kind p99 latency, mask-upload "
                         "p99 and fork overhead, cross-checks "
                         "generate + fork-row parity, and records "
                         "serving/kinds_* history rows")
    ap.add_argument("--sample-n", type=int, default=3,
                    help="--workload-mix: fork width of each sample "
                         "request (completions per prompt off one "
                         "shared prefill)")
    ap.add_argument("--constrain-ratio", type=float, default=0.25,
                    help="--workload-mix: share of the generate slice "
                         "to run as constrained (token-masked) "
                         "streams; 0 disables the mask path")
    ap.add_argument("--queryz-probe", action="store_true",
                    help="measure the wide-event plane on the live "
                         "engine: append overhead as a fraction of the "
                         "serving wall clock (asserted < 1%%) and the "
                         "median full-ring query latency; with "
                         "--record-history, writes serving/widevents_* "
                         "rows")
    ap.add_argument("--record-history", action="store_true",
                    help="append serving/* rows to bench_history.json for "
                         "scripts/check_bench_regression.py")
    ap.add_argument("--metrics-out", default=None)
    ap.add_argument("--trace-out", default=None,
                    help="enable spans; export the run as Chrome-trace "
                         "JSON (loads in Perfetto) at this path")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="arm the request-latency SLO: the report carries "
                         "serving_slo_violations_total so a load sweep "
                         "shows where the latency budget breaks")
    ap.add_argument("--request-trace-out", default=None,
                    help="record per-request timelines and export them as "
                         "Chrome-trace JSON, ONE LANE PER REQUEST — the "
                         "per-request view (queue wait -> prefill chunks "
                         "-> decode) --trace-out's per-thread lanes "
                         "cannot show (single-engine mode)")
    ap.add_argument("--skip-parity", action="store_true",
                    help="skip the generate() cross-check (pure load run)")
    args = ap.parse_args()
    _force_host_devices(args.force_host_devices)
    args._mesh = None

    from distkeras_tpu.serving import ServingMetrics

    tracer = None
    if args.trace_out:
        from distkeras_tpu.telemetry import enable_tracing

        tracer = enable_tracing()
    report = {"config": {
        "model": args.model, "slots": args.slots, "requests": args.requests,
        "new_tokens": args.new_tokens, "mode": args.mode,
        "prompt_len": args.prompt_len, "prefix_ratio": args.prefix_ratio,
        "prefix_count": args.prefix_count,
        "prefill_chunk": args.prefill_chunk,
        "prefix_cache_mb": args.prefix_cache_mb,
        "prefix_block": args.prefix_block,
        "paged": bool(args.paged or args.kv_pool_mb > 0),
        "kv_pool_mb": args.kv_pool_mb,
        "kv_block": args.kv_block,
        "max_context": args.max_context,
        "replicas": args.replicas,
        "pipeline_depth": args.pipeline_depth,
        "speculate": _speculating(args),
        "draft_model": (args.draft_model or args.model
                        if _speculating(args) else None),
        "spec_k": args.spec_k if _speculating(args) else 0,
        "mesh": (dict(_mesh(args).shape)
                 if (args.mesh or args.mesh_shape) else None),
    }}

    if args.pp_ab:
        # Pipeline-parallel depth sweep: its own phases, its own rows.
        model, variables = _model(args)
        try:
            all_results = asyncio.run(
                _pp_ab(args, model, variables, report))
            if not args.skip_parity:
                mism = _check_parity(model, variables, all_results,
                                     args.new_tokens)
                report["parity_mismatches"] = mism
                assert mism == 0, (
                    f"{mism} pp streams diverged from generate()")
            if args.min_speedup > 0:
                got = (report.get("pp_ab") or {}).get("speedup_x")
                assert got is not None and got >= args.min_speedup, (
                    f"pp depth speedup {got} < required "
                    f"{args.min_speedup}")
        finally:
            if tracer is not None:
                report["trace_out"] = tracer.export_chrome_trace(
                    args.trace_out)
        if args.record_history:
            _record_pp_history(args, report)
        print(json.dumps(report, indent=1))
        return

    if args.pipeline_ab:
        # Decode-pipeline A/B: its own phases, its own rows.
        model, variables = _model(args)
        try:
            all_results = asyncio.run(
                _pipeline_ab(args, model, variables, report))
            if not args.skip_parity:
                mism = _check_parity(model, variables, all_results,
                                     args.new_tokens)
                report["parity_mismatches"] = mism
                assert mism == 0, (
                    f"{mism} pipelined streams diverged from generate()")
            if args.min_speedup > 0:
                got = (report.get("pipeline_ab") or {}).get("speedup_x")
                assert got is not None and got >= args.min_speedup, (
                    f"pipeline speedup {got} < required "
                    f"{args.min_speedup}")
        finally:
            if tracer is not None:
                report["trace_out"] = tracer.export_chrome_trace(
                    args.trace_out)
        if args.record_history:
            _record_pipeline_history(args, report)
        print(json.dumps(report, indent=1))
        return

    if args.kv_tier:
        # Tiered-KV A/B: its own phases, its own rows. Tiering needs the
        # paged pool under it.
        if not (args.paged or args.kv_pool_mb > 0):
            args.paged = True
        report["config"]["paged"] = True
        report["config"]["kv_host_tier_mb"] = args.kv_host_tier_mb
        model, variables = _model(args)
        try:
            all_results = asyncio.run(
                _kv_tier_ab(args, model, variables, report))
            if not args.skip_parity:
                mism = _check_parity(model, variables, all_results,
                                     args.new_tokens)
                report["parity_mismatches"] = mism
                assert mism == 0, (
                    f"{mism} tiered streams diverged from generate()")
            asyncio.run(_kv_tier_push_phase(args, report))
            if args.kv_tier_strict:
                sec = report["kv_tier_ab"]
                assert sec["hit_rate_gain"] > 0, (
                    f"tiered prefix hit rate did not beat pool-only: "
                    f"gain {sec['hit_rate_gain']}")
                assert sec.get("ttft_p99_speedup_x", 0) > 1.0, (
                    f"tiered p99 TTFT did not beat pool-only: "
                    f"speedup {sec.get('ttft_p99_speedup_x')}")
        finally:
            if tracer is not None:
                report["trace_out"] = tracer.export_chrome_trace(
                    args.trace_out)
        if args.record_history:
            _record_kvtier_history(args, report)
        print(json.dumps(report, indent=1))
        return

    if args.slo:
        # Fleet telemetry + SLO acceptance: its own phases, its own
        # rows. Needs a fleet (the point is the MERGE) — default 3.
        args.replicas = max(args.replicas, 3)
        report["config"]["replicas"] = args.replicas
        report["config"]["slo"] = {
            "push_interval_s": args.slo_push_interval,
            "ttft_threshold_s": args.slo_ttft_threshold,
            "itl_threshold_s": args.slo_itl_threshold,
            "inject_delay_s": args.slo_inject_delay,
        }
        try:
            asyncio.run(_slo_bench(args, report))
            if args.slo_strict:
                pct = report["slo_bench"]["push"]["push_overhead_pct"]
                assert pct <= 2.0, (
                    f"telemetry push overhead {pct}% > 2% of the "
                    f"no-push baseline goodput")
        finally:
            if tracer is not None:
                report["trace_out"] = tracer.export_chrome_trace(
                    args.trace_out)
        if args.record_history:
            _record_slo_history(args, report)
        print(json.dumps(report, indent=1))
        return

    if args.workload_mix:
        # Mixed request-kind mode: its own phase, its own rows. Forked
        # sampling needs the paged pool under it (CoW block shares).
        if not (args.paged or args.kv_pool_mb > 0):
            args.paged = True
        report["config"]["paged"] = True
        report["config"]["workload_mix"] = args.workload_mix
        report["config"]["sample_n"] = args.sample_n
        report["config"]["constrain_ratio"] = args.constrain_ratio
        model, variables = _model(args)
        try:
            all_results = asyncio.run(
                _kinds_bench(args, model, variables, report))
            if not args.skip_parity:
                mism = _check_parity(model, variables, all_results,
                                     args.new_tokens)
                report["parity_mismatches"] = mism
                assert mism == 0, (
                    f"{mism} generate/fork streams diverged from "
                    f"generate()")
        finally:
            if tracer is not None:
                report["trace_out"] = tracer.export_chrome_trace(
                    args.trace_out)
        if args.record_history:
            _record_kinds_history(args, report)
        print(json.dumps(report, indent=1))
        return

    if args.tenants >= 2:
        # Adversarial multi-tenant mode: its own phases, its own rows.
        report["config"]["tenants"] = args.tenants
        model, variables = _model(args)
        try:
            asyncio.run(_qos_bench(args, model, variables, report))
        finally:
            if tracer is not None:
                report["trace_out"] = tracer.export_chrome_trace(
                    args.trace_out)
        if args.record_history:
            _record_qos_history(args, report)
        print(json.dumps(report, indent=1))
        return

    if args.roles:
        # Disaggregated fleet mode: prefill/decode roles with KV block
        # migration, optionally diffed against a monolithic fleet of
        # the same size. Rows land under serving/disagg_* — their OWN
        # series (client-observed fleet numbers diff against their own
        # prior, never the engine-direct series).
        roles = _parse_roles_spec(args.roles)
        if not (args.paged or args.kv_pool_mb > 0):
            args.paged = True  # migration needs the paged pool
        args.replicas = len(roles)
        report["config"]["roles"] = {
            "prefill": roles.count("prefill"),
            "decode": roles.count("decode")}
        report["config"]["paged"] = True
        baseline = None
        try:
            if args.disagg_baseline:
                braw: dict = {}
                asyncio.run(_cluster_bench(args, braw, roles=None))
                baseline = {m: braw[m] for m in ("closed", "open")
                            if isinstance(braw.get(m), dict)}
                report["monolithic_baseline"] = baseline
            model, variables, all_results = asyncio.run(
                _cluster_bench(args, report, roles=roles))
            if not args.skip_parity:
                mism = _check_parity(model, variables, all_results,
                                     args.new_tokens)
                report["parity_mismatches"] = mism
                assert mism == 0, \
                    f"{mism} disaggregated streams diverged from " \
                    f"generate()"
            if baseline:
                for mode in ("closed", "open"):
                    b = (baseline.get(mode) or {}).get("itl_p99_s")
                    d = (report.get(mode) or {}).get("itl_p99_s")
                    if b and d:
                        report[mode]["speedup_itl_x"] = round(b / d, 3)
            if args.min_itl_improvement > 0:
                got = (report.get("closed") or {}).get("speedup_itl_x")
                assert got is not None and \
                    got >= args.min_itl_improvement, (
                        f"closed-phase p99-ITL improvement "
                        f"{got} < required {args.min_itl_improvement}")
        finally:
            if tracer is not None:
                report["trace_out"] = tracer.export_chrome_trace(
                    args.trace_out)
        if args.record_history:
            _record_disagg_history(args, report, roles)
        print(json.dumps(report, indent=1))
        return

    if args.replicas >= 2:
        # Cluster path: same workload, driven over TCP through the
        # router. History rows are not recorded (client-observed numbers
        # are not comparable to the engine-direct series) — say so
        # instead of silently dropping the flag.
        if args.record_history:
            report["record_history_skipped"] = (
                "cluster-mode numbers are client-observed (router hop, "
                "retries) and not comparable to the engine-direct "
                "serving/* history series; no rows recorded")
        try:
            model, variables, all_results = asyncio.run(
                _cluster_bench(args, report))
            if not args.skip_parity:
                mism = _check_parity(model, variables, all_results,
                                     args.new_tokens)
                report["parity_mismatches"] = mism
                assert mism == 0, \
                    f"{mism} routed streams diverged from generate()"
        finally:
            if tracer is not None:
                report["trace_out"] = tracer.export_chrome_trace(
                    args.trace_out)
        print(json.dumps(report, indent=1))
        return

    model, variables, engine, stream = _build(args)

    async def run_mode(mode, phase):
        task = asyncio.create_task(engine.run())
        t0 = time.monotonic()
        if mode == "closed":
            results = await _closed_loop(
                engine, _prompts(args, args.requests, salt=phase), args)
            rejects = 0
        else:
            results, rejects = await _open_loop(
                engine, _prompts(args, args.requests, salt=phase), args)
        elapsed = time.monotonic() - t0
        engine.shutdown(drain=True)
        await task
        return results, rejects, elapsed

    async def run_all():
        # One event loop for every phase: asyncio primitives bind to the
        # loop they first run on, so sequential asyncio.run loops would
        # strand the engine's scheduler (reopen() also guards this).
        all_results = []
        for phase, mode in enumerate(["closed", "open"]
                                     if args.mode == "both"
                                     else [args.mode]):
            # Fresh metrics per phase (shared JSONL stream): the report's
            # per-mode percentiles must cover THIS load shape only, and
            # tokens_per_sec must divide by this phase's clock.
            engine.metrics = ServingMetrics(stream)
            if engine.slo_s is not None:
                # Re-arm the SLO gauge on the replacement registry, or
                # the phase summary would hide the violation counter.
                engine.metrics.set_slo(engine.slo_s)
            results, rejects, elapsed = await run_mode(mode, phase)
            all_results.extend(results)
            done_tokens = sum(len(t) for _, t in results)
            summary = engine.metrics.emit_summary()
            report[mode] = {
                "completed": len(results),
                "rejected_queue_full": rejects,
                "wall_s": round(elapsed, 3),
                "goodput_tokens_per_sec": round(done_tokens / elapsed, 2),
                **{k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in summary.items()
                   if k.startswith(("ttft", "inter_token", "queue", "slot",
                                    "tokens_per_sec", "requests",
                                    "prefill", "prefix", "slo", "kv_",
                                    "spec_", "host_gap", "device_idle"))},
            }
            engine.reopen()
        return all_results

    try:
        all_results = asyncio.run(run_all())
        if args.slot_sweep:
            # Fresh engines per point (slot count is compile-shape), own
            # event loop; streams from every point join the parity check
            # — preempt-and-requeue under sweep pressure must still be
            # token-identical.
            all_results.extend(asyncio.run(
                _run_slot_sweep(args, model, variables, report)))

        if engine.prefix_cache is not None:
            report["prefix_cache"] = engine.prefix_cache.stats()
        if engine.kv_pool is not None:
            report["kv_pool"] = engine.kv_pool.stats()
        compiles = engine.decode_compile_count()
        report["decode_compile_count"] = compiles
        assert compiles in (1, -1), (
            f"continuous batching retraced the decode step: {compiles} "
            "compiled executables (expected exactly 1)")
        if args.queryz_probe and engine.wide_events is not None:
            _queryz_probe(args, engine, report)
        if engine.auditor is not None and _speculating(args):
            # Speculative run: the armed auditor stayed silent (or we
            # would not be here) — record and assert the per-callable
            # counts: draft, verify, AND the fallback decode each
            # compiled exactly once. (Sharded-only runs need no extra
            # block: their single decode callable is the
            # decode_compile_count assertion right above.)
            spec_compiles = {
                name: engine.auditor.compiles(name)
                for name in ("serving_decode", "serving_draft",
                             "serving_verify")}
            report["spec_compiles"] = spec_compiles
            assert all(c == 1 for c in spec_compiles.values()), (
                f"speculation broke the compile-once contract: "
                f"{spec_compiles}")
        if not args.skip_parity:
            mism = _check_parity(model, variables, all_results,
                                 args.new_tokens)
            report["parity_mismatches"] = mism
            assert mism == 0, \
                f"{mism} streams diverged from one-shot generate()"
    finally:
        # Export even when an invariant fired: a failing run is exactly
        # when the admit/prefill/decode timeline is worth reading.
        if tracer is not None:
            report["trace_out"] = tracer.export_chrome_trace(args.trace_out)
        if engine.trace_store is not None and args.request_trace_out:
            report["request_trace_out"] = (
                engine.trace_store.export_chrome_trace(
                    args.request_trace_out))
        if stream is not None:
            stream.close()
    if args.record_history:
        _record_history(args, report)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
