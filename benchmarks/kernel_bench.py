"""Pallas kernels on silicon: flash attention + fused xent vs XLA paths.

For each shape: verify numerics against the dense/XLA implementation, then
time forward AND forward+backward, and sweep flash block sizes. Prints ONE
JSON line. On CPU the Pallas kernels run in interpret mode — numbers are
not meaningful there; run on the chip (VERDICT r1 weakness 5: the kernels
had never executed as compiled Mosaic).

  python benchmarks/kernel_bench.py            # default shapes
  BENCH_SEQS=1024,4096 python benchmarks/kernel_bench.py
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _bench(fn, *args, steps=10):
    out = fn(*args)
    import jax

    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps * 1e3  # ms


def main():
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        # The env var alone is NOT enough in this container (sitecustomize
        # pins axon first); the config update is what actually avoids
        # touching — and hanging on — a wedged chip.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from distkeras_tpu.ops.attention import dot_product_attention
    from distkeras_tpu.ops.pallas.flash_attention import flash_attention
    from distkeras_tpu.ops.pallas.fused_xent import fused_softmax_xent

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.default_rng(0)
    report: dict = {"metric": "pallas_kernel_bench", "backend": backend}

    # ---- flash attention: numerics + fwd/bwd timings + block sweep --------
    default_seqs = "1024,2048,4096" if on_tpu else "128"  # interpret is slow
    seqs = [int(s) for s in os.environ.get("BENCH_SEQS", default_seqs).split(",")]
    B, H, D = (4, 8, 64) if on_tpu else (1, 2, 32)
    attn = []
    for S in seqs:
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, S, H, D)) * 0.2, dtype)
            for _ in range(3)
        )
        dense_f = jax.jit(lambda q, k, v: dot_product_attention(q, k, v))
        flash_f = jax.jit(lambda q, k, v: flash_attention(q, k, v))
        # numerics: fwd + grads vs dense (bf16 tolerances)
        o_d, o_f = dense_f(q, k, v), flash_f(q, k, v)
        max_err = float(jnp.max(jnp.abs(o_d.astype(jnp.float32) - o_f.astype(jnp.float32))))
        g_d = jax.jit(jax.grad(lambda q, k, v: dense_f(q, k, v).astype(jnp.float32).sum()))(q, k, v)
        g_f = jax.jit(jax.grad(lambda q, k, v: flash_f(q, k, v).astype(jnp.float32).sum()))(q, k, v)
        grad_err = float(jnp.max(jnp.abs(g_d.astype(jnp.float32) - g_f.astype(jnp.float32))))

        dense_fb = jax.jit(jax.grad(lambda q: dense_f(q, k, v).astype(jnp.float32).sum()))
        flash_fb = jax.jit(jax.grad(lambda q: flash_f(q, k, v).astype(jnp.float32).sum()))
        entry = {
            "seq": S,
            "fwd_max_err": round(max_err, 5),
            "dq_max_err": round(grad_err, 5),
            "dense_fwd_ms": round(_bench(dense_f, q, k, v), 3),
            "flash_fwd_ms": round(_bench(flash_f, q, k, v), 3),
            "dense_fwdbwd_ms": round(_bench(dense_fb, q), 3),
            "flash_fwdbwd_ms": round(_bench(flash_fb, q), 3),
        }
        entry["fwd_speedup"] = round(entry["dense_fwd_ms"] / entry["flash_fwd_ms"], 2)
        entry["fwdbwd_speedup"] = round(
            entry["dense_fwdbwd_ms"] / entry["flash_fwdbwd_ms"], 2
        )
        attn.append(entry)
    report["flash_attention"] = attn

    # block-size sweep at the largest seq (VERDICT: 128/128 is a guess).
    # TPU only — on CPU interpret mode the sweep measures the interpreter.
    if on_tpu:
        S = seqs[-1]
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, S, H, D)) * 0.2, dtype)
            for _ in range(3)
        )
        sweep = []
        for bq in (128, 256, 512):
            for bk in (128, 256, 512):
                if S % bq or S % bk:
                    continue
                f = jax.jit(
                    lambda q, k, v, bq=bq, bk=bk: flash_attention(
                        q, k, v, block_q=bq, block_k=bk
                    )
                )
                try:
                    sweep.append(
                        {"bq": bq, "bk": bk, "ms": round(_bench(f, q, k, v), 3)}
                    )
                except Exception as e:  # VMEM overflow etc. — record, go on
                    sweep.append({"bq": bq, "bk": bk, "error": str(e)[:80]})
        ok = [s for s in sweep if "ms" in s]
        if ok:
            best = min(ok, key=lambda s: s["ms"])
            report["flash_block_sweep"] = {"seq": S, "best": best, "grid": sweep}

    # ---- fused xent: numerics + fwd/bwd timings ---------------------------
    T, V = (8192, 30522) if on_tpu else (256, 1024)
    logits = jnp.asarray(rng.normal(size=(T, V)), dtype)
    labels = jnp.asarray(rng.integers(0, V, size=(T,)), jnp.int32)

    def plain(lg, lb):
        lg = lg.astype(jnp.float32)
        lse = jax.nn.logsumexp(lg, axis=-1)
        return jnp.mean(lse - jnp.take_along_axis(lg, lb[:, None], 1)[:, 0])

    plain_f = jax.jit(plain)
    fused_f = jax.jit(fused_softmax_xent)
    xent_err = float(jnp.abs(plain_f(logits, labels) - fused_f(logits, labels)))
    plain_fb = jax.jit(jax.grad(plain))
    fused_fb = jax.jit(jax.grad(fused_softmax_xent))
    report["fused_xent"] = {
        "tokens": T,
        "vocab": V,
        "loss_abs_err": round(xent_err, 6),
        "plain_fwd_ms": round(_bench(plain_f, logits, labels), 3),
        "fused_fwd_ms": round(_bench(fused_f, logits, labels), 3),
        "plain_fwdbwd_ms": round(_bench(plain_fb, logits, labels), 3),
        "fused_fwdbwd_ms": round(_bench(fused_fb, logits, labels), 3),
    }

    print(json.dumps(report))


if __name__ == "__main__":
    main()
