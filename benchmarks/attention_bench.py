"""Flash vs dense attention: wall time and peak-memory proxy at long S.

On TPU the flash kernel avoids the S×S HBM intermediate and keeps the MXU
fed from VMEM tiles; on CPU this script still runs (interpret mode) but the
numbers are not meaningful — run on the chip. Prints one JSON line.

  BENCH_SEQ=4096 python benchmarks/attention_bench.py
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.ops.attention import dot_product_attention
    from distkeras_tpu.ops.pallas.flash_attention import flash_attention

    S = int(os.environ.get("BENCH_SEQ", "4096"))
    B, H, D = 4, 8, 64
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, S, H, D)), dtype) for _ in range(3)
    )

    def bench(fn, steps=10):
        out = fn(q, k, v)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(q, k, v)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / steps

    dense = jax.jit(lambda q, k, v: dot_product_attention(q, k, v))
    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v))

    t_dense = bench(dense)
    t_flash = bench(flash)
    scores_bytes = B * H * S * S * (2 if dtype == jnp.bfloat16 else 4)
    print(json.dumps({
        "metric": "flash_vs_dense_attention",
        "seq_len": S,
        "dense_ms": round(t_dense * 1e3, 2),
        "flash_ms": round(t_flash * 1e3, 2),
        "speedup": round(t_dense / t_flash, 2),
        "dense_scores_bytes": scores_bytes,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
