"""Sustained weight churn through a serving cluster: the continuous-
deployment benchmark.

The loop PRs 1-7 built is only trustworthy if reloads stay invisible
under *repeated* weight churn — one rolling reload proving zero-downtime
says little about the tenth. This bench runs the whole train->serve loop
shape in one process: a publisher task standing in for the trainer
(fresh weight versions published into a watch directory on a fixed
cadence), a LocalReplica cluster behind the router serving closed-loop
load the entire time, and a :class:`DeployController` canary-validating
and rolling every version. It measures and asserts:

- **zero downtime**: every client request completes across every canary
  drain + rolling reload (no client-visible error, ever);
- **provenance flips**: each completion names its ``(version, digest)``;
  the bench tracks the distinct versions observed and that the served
  version never moves backwards in completion order;
- **deploy latency**: manifest-seen -> fleet-verified, per deploy
  (p50/p95) — the staleness window between "trained" and "serving";
- **canary discipline**: with ``--corrupt-every K``, every K-th publish
  is NaN-poisoned and must be rejected without touching the fleet
  (``canary_pass_rate`` = good publishes deployed / good publishes);
- **compile-once**: every replica's decode step compiled exactly once
  across all of it.

``--record-history`` appends ``deploy/...`` rows to
``bench_history.json`` (``deploy_latency_*`` regresses UP,
``canary_pass_rate`` and goodput DOWN) for
``scripts/check_bench_regression.py``.

Run (CPU):
    JAX_PLATFORMS=cpu python benchmarks/deploy_bench.py \
        --replicas 2 --publishes 4 --publish-interval 2 --corrupt-every 3
"""

from __future__ import annotations

import argparse
import asyncio
import json
import tempfile
import time

import numpy as np


async def _run(args, report):
    import jax

    from distkeras_tpu.checkpoint import (
        load_weights_file_with_provenance,
        publish_weights,
    )
    from distkeras_tpu.deploy.harness import wire_controller
    from distkeras_tpu.models.bert import gpt_tiny
    from distkeras_tpu.serving import (
        LocalReplica,
        ServingClient,
        ServingCluster,
        ServingEngine,
    )
    from distkeras_tpu.serving.metrics import percentile
    from distkeras_tpu.telemetry import MetricsRegistry, RecompileAuditor

    model = gpt_tiny(seq_len=args.seq_len, vocab_size=args.vocab)
    variables = model.init(0)
    watch_dir = args.watch_dir or tempfile.mkdtemp(prefix="deploy-bench-")
    boot = publish_weights(watch_dir, variables, meta={"step": 0})

    engines = {}

    def factory(i):
        def build():
            v, prov = load_weights_file_with_provenance(
                boot["path"], like=variables)
            eng = ServingEngine(model, v, slots=args.slots,
                                max_queue=args.max_queue,
                                auditor=RecompileAuditor(),
                                arm_auditor_after_warmup=True,
                                weight_version=prov)
            engines[i] = eng
            return eng

        return LocalReplica(build)

    registry = MetricsRegistry()
    cluster = ServingCluster(
        factory, args.replicas, registry=registry,
        supervisor_kwargs=dict(health_interval_s=0.1, base_delay_s=0.2))
    rng = np.random.default_rng(args.seed)
    completions: list[tuple[float, dict]] = []
    client_errors: list[str] = []
    publishes = {"good": 0, "bad": 0}
    stop = asyncio.Event()

    async with cluster:
        port = cluster.port
        controller = wire_controller(
            cluster.router, watch_dir, model=model, vocab=args.vocab,
            golden_count=args.golden, golden_len=6, seed=args.seed,
            registry=registry, initial_weights=boot["path"],
            poll_interval_s=0.2)
        controller_task = asyncio.get_running_loop().create_task(
            controller.run())

        async def load_worker(k):
            async with ServingClient("127.0.0.1", port) as c:
                while not stop.is_set():
                    p = rng.integers(0, args.vocab,
                                     size=(3 + (k + len(completions)) % 5,)
                                     ).tolist()
                    try:
                        done = await c.generate(p, args.new_tokens)
                        completions.append(
                            (time.monotonic(), done["weight_version"]))
                    except Exception as e:
                        client_errors.append(repr(e))
                        return

        workers = [asyncio.create_task(load_worker(k))
                   for k in range(args.clients)]
        while len(completions) < args.clients:
            await asyncio.sleep(0.05)

        # The churn loop: the "trainer". Every --publish-interval a
        # fresh version lands; every --corrupt-every-th one is poisoned.
        # Each publish waits for the controller to consume it before the
        # next (a faster cadence would just coalesce at the manifest —
        # the controller always deploys the NEWEST version — and the
        # bench's per-deploy accounting wants 1:1).
        deadline = time.monotonic() + 600
        for k in range(1, args.publishes + 1):
            await asyncio.sleep(args.publish_interval)
            # Weight construction + serialization run OFF the loop: the
            # load clients, the health probes, and the controller all
            # share this one event loop, and a multi-second stall would
            # measure the bench harness, not the fleet.
            fresh = await asyncio.to_thread(model.init, k)
            bad = args.corrupt_every and k % args.corrupt_every == 0
            if bad:
                fresh = jax.tree.map(lambda x: np.asarray(x) * np.nan,
                                     fresh)
                publishes["bad"] += 1
            else:
                publishes["good"] += 1
            m = await asyncio.to_thread(
                publish_weights, watch_dir, fresh,
                meta={"step": k * 100, "loss": 1.0 / k})
            while (controller._seen_version < m["version"]
                   or controller.candidate is not None):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        "controller never caught up with the published "
                        "versions")
                await asyncio.sleep(0.1)
        # A few post-churn completions so the final version is observed.
        n_after = len(completions) + args.clients
        while len(completions) < n_after and not client_errors:
            await asyncio.sleep(0.05)
        stop.set()
        await asyncio.gather(*workers)
        controller.stop()
        await controller_task

        dz = controller.deployz()
        compiles = {f"r{i}": eng.auditor.compiles("serving_decode")
                    for i, eng in engines.items()}

    # -- report ---------------------------------------------------------
    versions = [wv.get("version") for _, wv in completions]
    distinct = sorted(set(versions))
    flips = sum(1 for a, b in zip(versions, versions[1:]) if a != b)
    deploy_latencies = [e["latency_s"] for e in dz["history"]
                       if e["status"] == "deployed"]
    wall = completions[-1][0] - completions[0][0] if completions else 1.0
    report["deploy"] = {
        "publishes": publishes,
        "deploys": dz["counters"]["deploys"],
        "canary_failures": dz["counters"]["canary_failures"],
        "validation_failures": dz["counters"]["validation_failures"],
        "rollbacks": dz["counters"]["rollbacks"],
        "canary_pass_rate": (
            round(dz["counters"]["deploys"] / publishes["good"], 4)
            if publishes["good"] else None),
        "deploy_latency_p50_s": (
            round(percentile(deploy_latencies, 50), 4)
            if deploy_latencies else None),
        "deploy_latency_p95_s": (
            round(percentile(deploy_latencies, 95), 4)
            if deploy_latencies else None),
        "served_versions_observed": distinct,
        "provenance_flips": flips,
        "quarantined": len(dz["quarantined"]),
    }
    report["serving"] = {
        "completed": len(completions),
        "client_errors": len(client_errors),
        "goodput_tokens_per_sec": round(
            len(completions) * args.new_tokens / wall, 2),
        "decode_compile_count": compiles,
    }
    report["deployz"] = dz

    # -- the contract ----------------------------------------------------
    assert not client_errors, (
        f"{len(client_errors)} client-visible errors under weight churn: "
        f"{client_errors[:3]}")
    assert dz["counters"]["deploys"] == publishes["good"], (
        "good publishes and completed deploys disagree: "
        f"{publishes} vs {dz['counters']}")
    assert dz["counters"]["canary_failures"] == publishes["bad"], (
        "every poisoned publish must be canary-rejected: "
        f"{publishes} vs {dz['counters']}")
    # Completion ORDER may interleave by one roll window (a replica
    # draining on the old version finishes alongside the first rolled
    # replica's new-version completions); the hard contract is that
    # every deployed version was actually served and the fleet ends on
    # the newest.
    assert len(distinct) == publishes["good"] + 1, (
        f"expected every deployed version observed on done lines: "
        f"{distinct}")
    assert versions and versions[0] == 1 and versions[-1] == distinct[-1]
    assert flips >= dz["counters"]["deploys"]
    assert all(c == 1 for c in compiles.values()), (
        f"decode retraced under weight churn: {compiles}")


# History rows: staleness-shaped metrics regress UP, rates/goodput DOWN.
_HISTORY_METRICS = (
    ("deploy", "deploy_latency_p50_s"),
    ("deploy", "deploy_latency_p95_s"),
    ("deploy", "canary_pass_rate"),
    ("serving", "goodput_tokens_per_sec"),
)


def _record_history(args, report):
    import os
    import sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench  # stdlib-only parent module

    path = os.path.join(root, "bench_history.json")
    hist = bench.load_history(path)
    base = (f"deploy/gpt_tiny/replicas{args.replicas}"
            f"/every{args.publish_interval:g}s")
    if args.corrupt_every:
        base += f"/corrupt{args.corrupt_every}"
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    for section, metric in _HISTORY_METRICS:
        v = report.get(section, {}).get(metric)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        key = f"{base}/{metric}"
        hist[key] = bench.history_entry(hist.get(key), float(v), when)
    bench.write_history(path, hist)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--max-queue", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--clients", type=int, default=3,
                    help="closed-loop concurrent clients through the "
                         "router, running for the whole churn")
    ap.add_argument("--new-tokens", type=int, default=4)
    ap.add_argument("--publishes", type=int, default=3,
                    help="weight versions published after boot")
    ap.add_argument("--publish-interval", type=float, default=2.0,
                    help="seconds between publishes (the trainer cadence)")
    ap.add_argument("--corrupt-every", type=int, default=0,
                    help="> 0: NaN-poison every K-th publish; the canary "
                         "must reject each one without touching the fleet")
    ap.add_argument("--golden", type=int, default=2,
                    help="golden prompts per canary")
    ap.add_argument("--watch-dir", default=None,
                    help="publish directory (default: fresh temp dir)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--record-history", action="store_true",
                    help="append deploy/* rows to bench_history.json for "
                         "scripts/check_bench_regression.py")
    args = ap.parse_args()

    report = {"config": {
        "replicas": args.replicas, "slots": args.slots,
        "clients": args.clients, "publishes": args.publishes,
        "publish_interval_s": args.publish_interval,
        "corrupt_every": args.corrupt_every, "golden": args.golden,
    }}
    asyncio.run(_run(args, report))
    if args.record_history:
        _record_history(args, report)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
