"""Native data-plane speedups vs pure Python/numpy.

Measures `native/fastdata.cpp` (ctypes) against the fallback paths for the
host-side hot ops: CSV parse, shuffle gather, batch pack. Prints one JSON
line. (The reference assembled minibatches row-by-row in Python inside
executors — its data path; SURVEY §3.1.)
"""

from __future__ import annotations

import csv as _csv
import io
import json
import time

import numpy as np

from distkeras_tpu.data import native


def timeit(fn, repeat=5):
    best = float("inf")
    for _ in range(repeat):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    assert native.available(), "build with: make -C native"
    rng = np.random.default_rng(0)

    # CSV parse: 20k rows x 29 cols
    rows, cols = 20000, 29
    mat = rng.normal(size=(rows, cols)).astype(np.float32)
    buf = io.StringIO()
    np.savetxt(buf, mat, fmt="%.6f", delimiter=",")
    data = buf.getvalue().encode()

    def py_parse():
        reader = _csv.reader(io.StringIO(data.decode()))
        return np.array([[float(v) for v in row] for row in reader], np.float32)

    t_native_parse = timeit(lambda: native.parse_csv(data, rows, cols), 3)
    t_py_parse = timeit(py_parse, 3)

    # gather: 1M rows x 32
    src = rng.normal(size=(1_000_000, 32)).astype(np.float32)
    idx = rng.permutation(1_000_000)
    t_native_gather = timeit(lambda: native.gather_rows(src, idx))
    t_np_gather = timeit(lambda: src[idx])

    # pack with fused normalize
    t_native_pack = timeit(
        lambda: native.pack_batch(src, 0, 65536, scale=1 / 255.0, shift=0.0)
    )
    t_np_pack = timeit(lambda: src[0:65536] * (1 / 255.0))

    print(json.dumps({
        "metric": "native_data_plane_speedup",
        "csv_parse": {
            "native_s": round(t_native_parse, 4), "python_s": round(t_py_parse, 4),
            "speedup": round(t_py_parse / t_native_parse, 1),
        },
        "shuffle_gather_1m": {
            "native_s": round(t_native_gather, 4), "numpy_s": round(t_np_gather, 4),
            "speedup": round(t_np_gather / t_native_gather, 2),
        },
        "fused_pack_normalize": {
            "native_s": round(t_native_pack, 4), "numpy_s": round(t_np_pack, 4),
            "speedup": round(t_np_pack / t_native_pack, 2),
        },
    }))


if __name__ == "__main__":
    main()
