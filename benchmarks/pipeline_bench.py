"""Pipeline schedule bench: GPipe (V=1) vs interleaved virtual stages (V=2+).

Same total model depth (L = pp * V layers), same microbatch count: the
interleaved schedule's fill/drain bubble is (P-1)/(M·V+P-1) vs GPipe's
(P-1)/(M+P-1), so wall-clock per step should drop toward the busy-time
floor as V grows. On the virtual CPU mesh the numbers are relative, not
TPU throughput; the schedule-length ratio is what to look at. Prints one
JSON line.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 python benchmarks/pipeline_bench.py

Memory mode (VERDICT r3 task 5 — the pipeline's activation-memory
accounting): ``BENCH_MODE=memory`` compiles the *train step* (grad through
``pipeline_apply`` over real transformer EncoderLayer stages) for each
schedule — GPipe-ordered autodiff plain vs ``remat`` stage_fn, V=1 vs 2 —
and reports **XLA's own per-device peak temp allocation**
(``Compiled.memory_analysis().temp_size_in_bytes``), i.e. measured
residency, not a hand model. Alongside each measured number it prints the
analytic saved-state floor (T ticks x microbatch state) and — measured
the same way — the TRUE 1F1B engine (parallel/pipeline_1f1b.py:
hand-rolled backward, ring buffer of <= P in-flight inputs, residency
independent of M), so the docs table's (model, M, V, P) fit claims trace
to this bench.

  BENCH_MODE=memory XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
      python benchmarks/pipeline_bench.py
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main():
    import jax

    if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from distkeras_tpu.parallel.mesh import make_mesh
    from distkeras_tpu.parallel.pipeline import (
        pipeline_apply,
        schedule_ticks,
        stack_stage_params,
    )

    P = int(os.environ.get("BENCH_PP", str(len(jax.devices()))))
    M = int(os.environ.get("BENCH_MICRO", "8"))
    D = int(os.environ.get("BENCH_DIM", "256"))
    B = int(os.environ.get("BENCH_MB", "8"))
    L = 2 * P  # total depth fixed; V=1 puts 2 layers/stage, V=2 puts 1
    mesh = make_mesh({"pp": P})
    rng = np.random.default_rng(0)

    def layer(w, x):
        return x + jnp.tanh(x @ w)

    weights = [
        np.asarray(rng.normal(size=(D, D)) * 0.2, np.float32) for _ in range(L)
    ]
    mb = np.asarray(rng.normal(size=(M, B, D)), np.float32)

    results = {}
    for V in (1, 2):
        per_stage = L // (P * V)
        groups = [
            {f"w{j}": weights[s * per_stage + j] for j in range(per_stage)}
            for s in range(P * V)
        ]

        def stage_fn(params, x, _n=per_stage):
            for j in range(_n):
                x = layer(params[f"w{j}"], x)
            return x

        stacked = stack_stage_params(groups, virtual_stages=V)
        fn = jax.jit(
            lambda sp, x, _V=V: pipeline_apply(
                stage_fn, sp, x, mesh, virtual_stages=_V
            )
        )
        out = fn(stacked, mb)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        steps = 20
        for _ in range(steps):
            out = fn(stacked, mb)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / steps
        ticks = schedule_ticks(M, P, V)
        busy = M * V  # per-device busy ticks (each 1/V the work of V=1 ticks)
        results[f"v{V}"] = {
            "ms": round(dt * 1e3, 2),
            "ticks": ticks,
            "bubble_frac": round((ticks - busy) / ticks, 3),
        }

    print(json.dumps({
        "metric": "pipeline_gpipe_vs_interleaved",
        "pp": P, "microbatches": M, "layers": L,
        **results,
        "speedup_v2_over_v1": round(
            results["v1"]["ms"] / results["v2"]["ms"], 3
        ),
        # On real parallel devices a tick at V is 1/V the work of a V=1
        # tick, so wall-clock ∝ ticks/V: this is the schedule-level win the
        # single-core CPU mesh cannot show (it serializes all devices, so
        # total work + per-tick overhead dominate there).
        "ideal_parallel_speedup_v2": round(
            results["v1"]["ticks"] / (results["v2"]["ticks"] / 2), 3
        ),
        "backend": jax.default_backend(),
    }))


def memory_mode():
    """Measured peak temp memory of the compiled pipelined train step, per
    schedule. One JSON line; see module docstring."""
    import jax

    if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from distkeras_tpu.models.bert import BertConfig, EncoderLayer
    from distkeras_tpu.parallel.mesh import make_mesh
    from distkeras_tpu.parallel.pipeline import (
        pipeline_apply,
        schedule_ticks,
        stack_stage_params,
    )

    P = int(os.environ.get("BENCH_PP", str(len(jax.devices()))))
    M = int(os.environ.get("BENCH_MICRO", "8"))
    D = int(os.environ.get("BENCH_DIM", "128"))
    S = int(os.environ.get("BENCH_SEQ", "64"))
    B_mb = int(os.environ.get("BENCH_MB", "4"))  # rows per microbatch
    mesh = make_mesh({"pp": P})
    cfg = BertConfig(
        vocab_size=64, hidden_size=D, num_heads=max(2, D // 64),
        mlp_dim=4 * D, max_seq_len=S, num_layers=2 * P, dtype=jnp.float32,
    )
    layer_mod = EncoderLayer(cfg)
    x_one = jnp.zeros((B_mb, S, D), jnp.float32)
    layer_params = [
        jax.tree.map(
            lambda m: m.unbox() if hasattr(m, "unbox") else m,
            layer_mod.init(jax.random.PRNGKey(i), x_one)["params"],
        )
        for i in range(2 * P)
    ]
    mb = np.zeros((M, B_mb, S, D), np.float32)
    state_bytes = B_mb * S * D * 4  # one microbatch activation, f32

    results = {}
    for V in (1, 2):
        per_stage = (2 * P) // (P * V)
        groups = [
            {
                f"sub_{j}": layer_params[s * per_stage + j]
                for j in range(per_stage)
            }
            for s in range(P * V)
        ]
        stacked = stack_stage_params(groups, virtual_stages=V)
        ticks = schedule_ticks(M, P, V)

        for remat in (False, True):
            def base_fn(params, x, _n=per_stage):
                for j in range(_n):
                    x = layer_mod.apply({"params": params[f"sub_{j}"]}, x)
                return x

            stage_fn = jax.checkpoint(base_fn) if remat else base_fn

            def loss(sp, x, _V=V, _fn=stage_fn):
                y = pipeline_apply(_fn, sp, x, mesh, virtual_stages=_V)
                return jnp.sum(y * y)

            compiled = jax.jit(jax.grad(loss)).lower(stacked, mb).compile()
            ma = compiled.memory_analysis()
            key = f"v{V}_{'remat' if remat else 'plain'}"
            results[key] = {
                "measured_temp_mb": round(ma.temp_size_in_bytes / 2**20, 2),
                "args_mb": round(ma.argument_size_in_bytes / 2**20, 2),
                "ticks": ticks,
                # Scan-autodiff floor: every tick's carried state is saved
                # for the backward (remat removes the per-layer internals,
                # not the carries).
                "analytic_saved_state_mb": round(
                    ticks * state_bytes / 2**20, 2
                ),
            }

    # --- true 1F1B (hand-rolled backward, parallel/pipeline_1f1b.py) ----
    # Same per-stage depth as the V=1 schedules (2 layers/stage), head +
    # loss fused into the last stage as the engine requires.
    from distkeras_tpu.parallel.pipeline_1f1b import (
        pipeline_1f1b_value_and_grad,
        ticks_1f1b,
    )

    def stage2(params, x):
        for j in range(2):
            x = layer_mod.apply({"params": params[f"sub_{j}"]}, x)
        return x

    def last_fn(params, hp, x, labels_mb):
        y = stage2(params, x)
        return jnp.sum((y @ hp["w"] - labels_mb) ** 2)

    head = {"w": np.zeros((D, 8), np.float32)}
    labels = np.zeros((M, B_mb, S, 8), np.float32)
    groups2 = [
        {f"sub_{j}": layer_params[s * 2 + j] for j in range(2)}
        for s in range(P)
    ]
    stacked2 = stack_stage_params(groups2)
    compiled = jax.jit(
        lambda sp, hp, x, y: pipeline_1f1b_value_and_grad(
            stage2, last_fn, sp, hp, x, y, mesh
        )
    ).lower(stacked2, head, mb, labels).compile()
    ma = compiled.memory_analysis()
    # Hop accounting (round 5): the phase-split scan elides the fill
    # phase's P cotangent hops and the drain phase's P activation hops —
    # each direction permutes on 2M+P-2 of the 2M+2P-2 ticks instead of
    # all of them.
    t1f = ticks_1f1b(M, P)
    hops = 2 * M + P - 2
    results["true_1f1b"] = {
        "measured_temp_mb": round(ma.temp_size_in_bytes / 2**20, 2),
        "args_mb": round(ma.argument_size_in_bytes / 2**20, 2),
        "ticks": ticks_1f1b(M, P),
        "ppermute_hops_per_dir": hops,
        "ppermute_hops_elided": t1f - hops,
        "hop_bytes_saved_per_step_mb": round(
            (t1f - hops) * state_bytes * 2 / 2**20, 2
        ),
        # The ring holds <= P in-flight microbatch inputs per device; the
        # carry also holds ONE M-sized f32 input-cotangent buffer
        # (cot_out), so the floor is (min(P, M) + M) states — linear in M
        # with a far smaller constant than the scanned schedules' per-tick
        # saves (ticks ~ 2M states each, times stage internals).
        "analytic_saved_state_mb": round(
            (min(P, M) + M) * state_bytes / 2**20, 2
        ),
    }

    # --- interleaved-V2 vs 1F1B-at-2M: the equal-bubble comparison ------
    # Schedule fact: 1F1B at M'=2M has bubble (P-1)/(2M+P-1) — EXACTLY the
    # V=2 interleaved schedule's fraction at M. Both do remat-equivalent
    # compute (one recompute per stage application), so measuring 1F1B's
    # temp at 2M against v2_remat's at M compares the two bubble-reduction
    # strategies (interleave chunks vs raise M under a flat-memory
    # schedule) at equal pipeline efficiency. This is the measured case
    # for keeping V>1 on the scanned schedule only (VERDICT r4 task 6):
    # doubling M under 1F1B costs ~one extra cot_out buffer; interleaving
    # under scan-autodiff costs the whole tick-state save.
    mb2 = np.zeros((2 * M, B_mb, S, D), np.float32)
    labels2 = np.zeros((2 * M, B_mb, S, 8), np.float32)
    compiled = jax.jit(
        lambda sp, hp, x, y: pipeline_1f1b_value_and_grad(
            stage2, last_fn, sp, hp, x, y, mesh
        )
    ).lower(stacked2, head, mb2, labels2).compile()
    ma2 = compiled.memory_analysis()
    bubble = round((P - 1) / (2 * M + P - 1), 3)
    results["schedule_tradeoff_equal_bubble"] = {
        "bubble_frac": bubble,
        "v2_remat_at_M_temp_mb": results["v2_remat"]["measured_temp_mb"],
        "true_1f1b_at_2M_temp_mb": round(ma2.temp_size_in_bytes / 2**20, 2),
        "memory_ratio": round(
            results["v2_remat"]["measured_temp_mb"]
            / max(1e-9, ma2.temp_size_in_bytes / 2**20), 2
        ),
        "note": "same bubble, same remat-equivalent compute: raising M "
                "under 1F1B beats interleaving V under scan-autodiff on "
                "memory; interleaved-1F1B only pays when M is capped by "
                "the global batch (see docs/parallel.md)",
    }

    # --- MoE x ep 1F1B (round 5: the composed flagship) -----------------
    # Same measurement for the hand-rolled schedule with an MoE trunk and
    # experts sharded over ep (pp x ep mesh): the flat-in-M claim must
    # survive the composition, so we compile at M and 2M and report both,
    # plus the gpipe-autodiff equivalent at M for contrast.
    if P % 2 == 0:
        from distkeras_tpu.parallel.pipeline_1f1b import (
            pipeline_1f1b_value_and_grad,
        )
        from jax.sharding import NamedSharding

        ep = 2
        pp_moe = P // ep
        mesh_moe = make_mesh({"pp": pp_moe, "ep": ep})
        cfg_moe = BertConfig(
            vocab_size=64, hidden_size=D, num_heads=max(2, D // 64),
            mlp_dim=4 * D, max_seq_len=S, num_layers=2 * pp_moe,
            dtype=jnp.float32, moe_experts=4,
        )
        from flax import linen as fnn

        full_layer = EncoderLayer(cfg_moe)  # full-E init (trainer parity)
        ep_layer = EncoderLayer(cfg_moe, ep_axis="ep", ep_size=ep)
        moe_params = [
            fnn.meta.unbox(
                full_layer.init(jax.random.PRNGKey(i), x_one)
            )["params"]
            for i in range(2 * pp_moe)
        ]
        groups_moe = [
            {f"sub_{j}": moe_params[s * 2 + j] for j in range(2)}
            for s in range(pp_moe)
        ]
        stacked_moe = stack_stage_params(groups_moe)

        from distkeras_tpu.parallel.pipeline import stage_param_specs

        specs_moe = stage_param_specs(stacked_moe, ep_size=ep)
        stacked_moe = jax.tree.map(
            lambda v, s: jax.device_put(v, NamedSharding(mesh_moe, s)),
            stacked_moe, specs_moe,
        )

        def moe_stage(params, x):
            aux = jnp.float32(0.0)
            for j in range(2):
                x, st = ep_layer.apply(
                    {"params": params[f"sub_{j}"]}, x, mutable=["aux_loss"]
                )
                aux = aux + sum(
                    jnp.sum(v) for v in jax.tree.leaves(st["aux_loss"])
                )
            return x, aux

        def moe_last(params, hp, x, labels_mb):
            y, aux = moe_stage(params, x)
            return jnp.sum((y @ hp["w"] - labels_mb) ** 2), aux

        head_moe = {"w": np.zeros((D, 8), np.float32)}
        for tag, M_i in (("moe_1f1b", M), ("moe_1f1b_2m", 2 * M)):
            mb_i = np.zeros((M_i, B_mb, S, D), np.float32)
            lab_i = np.zeros((M_i, B_mb, S, 8), np.float32)
            compiled = jax.jit(
                lambda sp, hp, x, y: pipeline_1f1b_value_and_grad(
                    moe_stage, moe_last, sp, hp, x, y, mesh_moe,
                    param_specs=specs_moe, stage_aux_seed=0.01,
                )
            ).lower(stacked_moe, head_moe, mb_i, lab_i).compile()
            ma = compiled.memory_analysis()
            results[tag] = {
                "measured_temp_mb": round(ma.temp_size_in_bytes / 2**20, 2),
                "microbatches": M_i,
                "ticks": ticks_1f1b(M_i, pp_moe),
            }

        # gpipe-autodiff contrast at M (same trunk, scanned schedule).
        def gpipe_moe_loss(sp, hp, x, y):
            out, aux = pipeline_apply(
                moe_stage, sp, x, mesh_moe, with_aux=True,
                param_specs=specs_moe,
            )
            return (
                jnp.sum((out @ hp["w"] - y) ** 2) + 0.01 * aux
            )

        mb_m = np.zeros((M, B_mb, S, D), np.float32)
        lab_m = np.zeros((M, B_mb, S, 8), np.float32)
        compiled = jax.jit(
            jax.grad(gpipe_moe_loss)
        ).lower(stacked_moe, head_moe, mb_m, lab_m).compile()
        ma = compiled.memory_analysis()
        results["moe_gpipe_plain"] = {
            "measured_temp_mb": round(ma.temp_size_in_bytes / 2**20, 2),
            "microbatches": M,
        }

    print(json.dumps({
        "metric": "pipeline_activation_memory",
        "pp": P, "microbatches": M, "layers": 2 * P, "hidden": D,
        "seq": S, "microbatch_rows": B_mb,
        "state_bytes_per_microbatch": state_bytes,
        **results,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_MODE") == "memory":
        memory_mode()
    else:
        main()
