"""Pipeline schedule bench: GPipe (V=1) vs interleaved virtual stages (V=2+).

Same total model depth (L = pp * V layers), same microbatch count: the
interleaved schedule's fill/drain bubble is (P-1)/(M·V+P-1) vs GPipe's
(P-1)/(M+P-1), so wall-clock per step should drop toward the busy-time
floor as V grows. On the virtual CPU mesh the numbers are relative, not
TPU throughput; the schedule-length ratio is what to look at. Prints one
JSON line.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 python benchmarks/pipeline_bench.py
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main():
    import jax

    if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from distkeras_tpu.parallel.mesh import make_mesh
    from distkeras_tpu.parallel.pipeline import (
        pipeline_apply,
        stack_stage_params,
    )

    P = int(os.environ.get("BENCH_PP", str(len(jax.devices()))))
    M = int(os.environ.get("BENCH_MICRO", "8"))
    D = int(os.environ.get("BENCH_DIM", "256"))
    B = int(os.environ.get("BENCH_MB", "8"))
    L = 2 * P  # total depth fixed; V=1 puts 2 layers/stage, V=2 puts 1
    mesh = make_mesh({"pp": P})
    rng = np.random.default_rng(0)

    def layer(w, x):
        return x + jnp.tanh(x @ w)

    weights = [
        np.asarray(rng.normal(size=(D, D)) * 0.2, np.float32) for _ in range(L)
    ]
    mb = np.asarray(rng.normal(size=(M, B, D)), np.float32)

    results = {}
    for V in (1, 2):
        per_stage = L // (P * V)
        groups = [
            {f"w{j}": weights[s * per_stage + j] for j in range(per_stage)}
            for s in range(P * V)
        ]

        def stage_fn(params, x, _n=per_stage):
            for j in range(_n):
                x = layer(params[f"w{j}"], x)
            return x

        stacked = stack_stage_params(groups, virtual_stages=V)
        fn = jax.jit(
            lambda sp, x, _V=V: pipeline_apply(
                stage_fn, sp, x, mesh, virtual_stages=_V
            )
        )
        out = fn(stacked, mb)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        steps = 20
        for _ in range(steps):
            out = fn(stacked, mb)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / steps
        ticks = ((M - 1) // P) * V * P + ((M - 1) % P) + V * P
        busy = M * V  # per-device busy ticks (each 1/V the work of V=1 ticks)
        results[f"v{V}"] = {
            "ms": round(dt * 1e3, 2),
            "ticks": ticks,
            "bubble_frac": round((ticks - busy) / ticks, 3),
        }

    print(json.dumps({
        "metric": "pipeline_gpipe_vs_interleaved",
        "pp": P, "microbatches": M, "layers": L,
        **results,
        "speedup_v2_over_v1": round(
            results["v1"]["ms"] / results["v2"]["ms"], 3
        ),
        # On real parallel devices a tick at V is 1/V the work of a V=1
        # tick, so wall-clock ∝ ticks/V: this is the schedule-level win the
        # single-core CPU mesh cannot show (it serializes all devices, so
        # total work + per-tick overhead dominate there).
        "ideal_parallel_speedup_v2": round(
            results["v1"]["ticks"] / (results["v2"]["ticks"] / 2), 3
        ),
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
