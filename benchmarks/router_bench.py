"""Router front-door ceiling: requests/s through the cluster router with
NO model behind it.

Every other serving benchmark measures decode; this one isolates the
FRONT DOOR — the cost ROADMAP item 3 calls the wall at production QPS:
readline + json loads/dumps per message at client, router, and replica.
The fleet is :class:`~distkeras_tpu.serving.cluster.replicas.EchoServer`
(protocol-complete, engine-free: each request is answered with
``--echo-tokens`` token events and a done line), so wall time is pure
wire + router cost and the measured number is the router's requests/s
CEILING, not a decode throughput.

Methodology: the router runs ALONE in this process; echo replicas and
load-generating clients are separate OS processes (fork), so the
router's single event loop is the measured resource — in-process
clients would bill their own wire cost to the router's core and mask
the ceiling. Client processes warm up (connect + negotiate + a few
round trips), meet at a barrier, then drive the timed run.

Two wire modes, measured in one invocation:

- ``jsonl`` — the BEFORE number: a ``wire='jsonl'`` router (binary
  upgrade disabled in BOTH directions, i.e. the pre-bin1 code path:
  exclusive pooled backend connections, one readline + json round per
  message) under sequential-per-connection JSONL clients;
- ``bin1`` — the AFTER number: a ``wire='auto'`` router with the
  negotiated binary front door — multiplexed per-replica backend
  connections, pipelined client streams, batched frame reads, and
  coalesced token writes.

``--record-history`` writes ``serving/router_echo/...`` rows
(requests/s per wire + the bin1/jsonl ``speedup_x``) under the same
strict ``--only serving/`` CI gate as every serving row, and
``--min-speedup X`` turns the ratio into a hard assertion — the
acceptance run uses ``--min-speedup 5``.

Run (no jax/accelerator needed — pure asyncio + the native wire core):

    python benchmarks/router_bench.py --requests 20000 --replicas 2 \
        --client-procs 4 --pipeline 64 --min-speedup 5 --record-history
"""

from __future__ import annotations

import argparse
import asyncio
import json
import multiprocessing as mp
import os
import sys
import time

# Self-contained even without `pip install -e .`: nothing here needs
# jax, so this bench must run anywhere the checkout exists.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


# -- child processes --------------------------------------------------------
def _echo_proc(conn, echo_tokens: int, kv_block_tokens: int = 4) -> None:
    """One echo replica in its own process: bind, report the port, serve
    until killed. ``kv_block_tokens`` sizes the emulated KV blocks so a
    roles run's short bench prompts still export/adopt chains."""
    from distkeras_tpu.serving.cluster.replicas import EchoServer

    async def run():
        server = EchoServer(echo_tokens=echo_tokens,
                            kv_block_tokens=kv_block_tokens)
        await server.start()
        conn.send(("127.0.0.1", server.port))
        await asyncio.Event().wait()  # until SIGTERM

    try:
        asyncio.run(run())
    except (KeyboardInterrupt, SystemExit):
        pass


def _client_proc(conn, barrier, port: int, wire_name: str, requests: int,
                 conns: int, pipeline: int, prompt_len: int) -> None:
    """One load-generator process: warm up its connections, wait at the
    barrier with every other client, drive its share of the load, and
    report (wall_s, completed, latency samples)."""

    async def run():
        from distkeras_tpu.serving import ServingClient

        all_prompts = [[(i % 250) + 1] * prompt_len
                       for i in range(requests)]
        latencies: list[float] = []
        completed = 0

        async def worker_jsonl(c, prompts):
            nonlocal completed
            for p in prompts:
                t0 = time.monotonic()
                await c.generate(p, 1)
                latencies.append(time.monotonic() - t0)
                completed += 1

        async def worker_bin1(c, prompts):
            # Waves of `pipeline` requests per connection: one buffered
            # write of REQ frames, futures resolved by the demux loop —
            # the batched-admission client shape. Latency here is
            # time-to-complete for a request inside its wave.
            nonlocal completed
            for i in range(0, len(prompts), pipeline):
                wave = prompts[i:i + pipeline]
                t0 = time.monotonic()
                dones = await c.generate_batch(wave, 1)
                dt = time.monotonic() - t0
                ok = sum(1 for d in dones if isinstance(d, dict))
                completed += ok
                latencies.extend([dt] * ok)

        clients = []
        share = len(all_prompts) // conns or 1
        for i in range(conns):
            c = ServingClient("127.0.0.1", port,
                              wire_mode="bin1" if wire_name == "bin1"
                              else "jsonl")
            await c.connect()
            await c.generate([1, 2], 1)  # warm the route
            clients.append((c, all_prompts[i * share:(i + 1) * share]
                            if i < conns - 1
                            else all_prompts[i * share:]))
        barrier.wait(timeout=60)
        t0 = time.monotonic()
        worker = worker_bin1 if wire_name == "bin1" else worker_jsonl
        await asyncio.gather(*(worker(c, ps) for c, ps in clients))
        wall = time.monotonic() - t0
        for c, _ in clients:
            await c.aclose()
        # Ship a bounded latency sample (the parent computes percentiles
        # over the union; full lists would be MBs at high request counts).
        step = max(1, len(latencies) // 2000)
        conn.send((wall, completed, latencies[::step]))

    asyncio.run(run())


class _ProcEchoReplica:
    """ReplicaHandle over an out-of-process EchoServer (fork + pipe
    port handshake) — the router's event loop never shares a core with
    the replicas it routes to."""

    def __init__(self, echo_tokens: int = 1):
        self._parent, child = mp.Pipe()
        self.proc = mp.Process(target=_echo_proc,
                               args=(child, echo_tokens), daemon=True)
        self.proc.start()

    async def start(self):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self._parent.recv)

    @property
    def alive(self) -> bool:
        return self.proc.is_alive()

    async def kill(self) -> None:
        if self.proc.is_alive():
            self.proc.terminate()

    async def terminate(self) -> None:
        await self.kill()


# -- measurement ------------------------------------------------------------
async def _measure(args, wire_name: str) -> dict:
    """One wire mode's ceiling: fresh router (policy per mode: the
    jsonl BEFORE router refuses the upgrade everywhere, recreating the
    pre-bin1 path exactly), fresh client processes, timed between the
    barrier release and the last client's completion."""
    from distkeras_tpu.serving.cluster.router import Router
    from distkeras_tpu.serving.cluster.supervisor import ReplicaSupervisor
    from distkeras_tpu.serving.metrics import percentile
    from distkeras_tpu.telemetry import MetricsRegistry

    roles = getattr(args, "_roles", None)  # parsed once in main()
    supervisor = ReplicaSupervisor(
        lambda i: _ProcEchoReplica(args.echo_tokens),
        args.replicas, health_interval_s=5.0, roles=roles)
    await supervisor.start()
    registry = MetricsRegistry() if roles else None
    router = Router(supervisor, port=0, registry=registry,
                    trace_capacity=512 if args.trace else 0,
                    wire_mode="jsonl" if wire_name == "jsonl" else "auto",
                    # Bench prompts are short; hand off anything with
                    # at least one emulated block.
                    min_handoff_tokens=4)
    await router.start()
    procs, conns = [], []
    n_procs = args.client_procs
    share = args.requests // n_procs
    barrier = mp.Barrier(n_procs + 1)
    try:
        for _ in range(n_procs):
            parent, child = mp.Pipe()
            p = mp.Process(
                target=_client_proc,
                args=(child, barrier, router.port, wire_name, share,
                      args.conns_per_proc, args.pipeline,
                      args.prompt_len),
                daemon=True)
            p.start()
            procs.append(p)
            conns.append(parent)
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, barrier.wait, 120)
        t0 = time.monotonic()
        results = await asyncio.gather(*(
            loop.run_in_executor(None, c.recv) for c in conns))
        wall = time.monotonic() - t0
        completed = sum(r[1] for r in results)
        lats = [x for r in results for x in r[2]]
        sec = {
            "requests": completed,
            "wall_s": round(wall, 4),
            "requests_per_sec": round(completed / wall, 1),
            "client_procs": n_procs,
            "conns_per_proc": args.conns_per_proc,
        }
        if wire_name == "bin1":
            sec["pipeline"] = args.pipeline
        if lats:
            sec["latency_p50_s"] = round(percentile(lats, 50), 6)
            sec["latency_p99_s"] = round(percentile(lats, 99), 6)
        sec["backend_wire"] = {
            rid: info.wire_proto
            for rid, info in supervisor.replicas.items()}
        if roles:
            snap = registry.snapshot()
            sec["roles"] = {"prefill": roles.count("prefill"),
                            "decode": roles.count("decode")}
            sec["kv_handoffs"] = snap.get(
                "router_kv_handoffs_total", {}).get("value", 0)
            sec["kv_handoff_fallbacks"] = snap.get(
                "router_kv_handoff_fallbacks_total", {}).get("value", 0)
        return sec
    finally:
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
        await router.stop()
        await supervisor.stop()


# History rows: requests_per_sec and speedup_x regress by DROPPING
# (higher-is-better, the checker's default); latency_* rows by rising.
_ROW_METRICS = ("requests_per_sec", "latency_p50_s", "latency_p99_s")


def _record_history(args, report: dict) -> None:
    import time as _time

    import bench  # stdlib-only shared history helpers (repo root)

    path = os.path.join(_ROOT, "bench_history.json")
    hist = bench.load_history(path)
    when = _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime())
    base = (f"serving/router_echo/replicas{args.replicas}"
            f"/procs{args.client_procs}x{args.conns_per_proc}")
    for wire_name in ("jsonl", "bin1"):
        sec = report.get(wire_name)
        if not isinstance(sec, dict):
            continue
        tag = (f"{wire_name}/pipeline{args.pipeline}"
               if wire_name == "bin1" else wire_name)
        for metric in _ROW_METRICS:
            v = sec.get(metric)
            if isinstance(v, (int, float)) and v > 0:
                key = f"{base}/{tag}/{metric}"
                hist[key] = bench.history_entry(hist.get(key), float(v),
                                                when)
    speedup = report.get("speedup_x")
    if isinstance(speedup, (int, float)) and speedup > 0:
        key = f"{base}/pipeline{args.pipeline}/speedup_x"
        hist[key] = bench.history_entry(hist.get(key), float(speedup),
                                        when)
    bench.write_history(path, hist)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=20000,
                    help="generation requests per wire mode (split "
                         "across client processes)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="echo replica processes behind the router")
    ap.add_argument("--client-procs", type=int, default=4,
                    help="load-generator processes")
    ap.add_argument("--conns-per-proc", type=int, default=4,
                    help="connections per client process")
    ap.add_argument("--pipeline", type=int, default=64,
                    help="bin1: concurrent multiplexed streams per "
                         "connection (jsonl is pinned to 1 by its own "
                         "protocol)")
    ap.add_argument("--prompt-len", type=int, default=8,
                    help="tokens per request prompt")
    ap.add_argument("--roles", default=None, metavar="prefill=N,decode=M",
                    help="disaggregated echo fleet: the router prefills "
                         "each prompt on an (emulated) prefill replica "
                         "and decode replicas run the REAL KVBLK pull "
                         "before echoing — measures the handoff path's "
                         "router cost jax-free (overrides --replicas; "
                         "disables the zero-task fast path by design)")
    ap.add_argument("--echo-tokens", type=int, default=1,
                    help="token events per echoed request")
    ap.add_argument("--wire", default="both",
                    choices=["jsonl", "bin1", "both"],
                    help="which front door(s) to measure")
    ap.add_argument("--trace", action="store_true",
                    help="keep the router's per-request timeline store ON "
                         "(measures the observability tax; default off "
                         "for the pure ceiling)")
    ap.add_argument("--min-speedup", type=float, default=0.0,
                    help="with --wire both: assert bin1 requests/s >= "
                         "this multiple of jsonl's (the acceptance run "
                         "uses 5)")
    ap.add_argument("--record-history", action="store_true",
                    help="append serving/router_* rows to "
                         "bench_history.json for the strict CI gate")
    args = ap.parse_args()
    args._roles = None
    if args.roles:
        from benchmarks.serving_bench import _parse_roles_spec

        args._roles = _parse_roles_spec(args.roles)
        args.replicas = len(args._roles)

    report: dict = {"config": {
        "requests": args.requests, "replicas": args.replicas,
        "client_procs": args.client_procs,
        "conns_per_proc": args.conns_per_proc,
        "pipeline": args.pipeline, "prompt_len": args.prompt_len,
        "echo_tokens": args.echo_tokens, "trace": bool(args.trace),
        "roles": args.roles,
    }}
    for wire_name in (("jsonl", "bin1") if args.wire == "both"
                      else (args.wire,)):
        report[wire_name] = asyncio.run(_measure(args, wire_name))
    if "jsonl" in report and "bin1" in report:
        report["speedup_x"] = round(
            report["bin1"]["requests_per_sec"]
            / report["jsonl"]["requests_per_sec"], 2)
    if args.record_history:
        _record_history(args, report)
    print(json.dumps(report, indent=1))
    if args.min_speedup > 0:
        speedup = report.get("speedup_x", 0.0)
        assert speedup >= args.min_speedup, (
            f"bin1 front door is only {speedup}x the jsonl ceiling "
            f"(required >= {args.min_speedup}x)")


if __name__ == "__main__":
    main()
