"""Step-time variance: socket-PS-style async vs GSPMD all-reduce.

BASELINE.json's second metric is "PS→all-reduce step-time variance": the
reference's socket parameter server serialized all workers' commits through
one lock, making step times jittery; the GSPMD all-reduce path is lock-step
and should show near-zero variance. This benchmark measures both on the
same model/data and prints a JSON comparison.

Runs anywhere: real TPU (1 chip: async threads share the chip) or the
8-device virtual CPU mesh:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/step_variance.py --platform cpu
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None, choices=[None, "cpu", "tpu"])
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=60)
    # adag measures the fused bf16 delta-window wire (round-2 comparable);
    # aeasgd measures the round-4 delta-encoded elastic exchange
    # (bit-identical bf16 mirrors both sides — VERDICT r4 task 4 asks for
    # the async column to track the wire that actually changed).
    ap.add_argument("--protocol", default="adag", choices=["adag", "aeasgd"])
    args = ap.parse_args()

    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models.core import Model
    from distkeras_tpu.models.mlp import MLP

    rng = np.random.default_rng(0)
    d = 256
    n = args.steps * args.batch_size * args.workers
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)

    def model():
        return Model.from_flax(
            MLP(features=(512, 512), num_classes=2), input_shape=(d,)
        )

    # --- async PS path (per-worker step times from history timestamps) ----
    class TimingStream:
        def __init__(self):
            self.t = []

        def emit(self, step, metrics):
            pass

    t0 = time.time()
    if args.protocol == "aeasgd":
        async_trainer = dk.AEASGD(
            model(), worker_optimizer="sgd", learning_rate=0.05,
            num_workers=args.workers, batch_size=args.batch_size,
            num_epoch=1, communication_window=4, rho=1.0,
        )
    else:
        async_trainer = dk.ADAG(
            model(), worker_optimizer="sgd", learning_rate=0.05,
            num_workers=args.workers, batch_size=args.batch_size,
            num_epoch=1, communication_window=4,
        )
    async_trainer.train(ds)
    async_wall = time.time() - t0
    async_steps = len(async_trainer.get_history())
    async_mean = async_wall / max(1, async_steps / args.workers)

    # Steady state: drop each worker's first window (it absorbs the one-off
    # XLA compile, which the sync path's timed loop also excludes).
    wt = async_trainer.window_times
    warm_start = max(h[0][0] for h in wt if h)  # all workers past compile
    steady_steps = sum(n for h in wt for (t, n) in h if t > warm_start)
    t_end = max(t for h in wt for (t, _) in h)
    async_steady = (
        (t_end - warm_start) / max(1, steady_steps / args.workers)
        if steady_steps
        else async_mean
    )

    # --- sync all-reduce path (explicit per-step timing) -------------------
    from distkeras_tpu.data.feed import minibatches
    from distkeras_tpu.ops.losses import get_optimizer
    from distkeras_tpu.parallel.mesh import best_mesh, data_parallel_shardings
    from distkeras_tpu.training.step import TrainState, make_train_step

    mesh = best_mesh()
    ndev = mesh.devices.size
    bs_global = args.batch_size * ndev
    m = model()
    opt = get_optimizer("sgd", 0.05)
    step_fn = make_train_step(m, opt, "categorical_crossentropy", metrics=())
    state = TrainState.create(m, opt, rng=0)
    batch_sh, repl = data_parallel_shardings(mesh)
    state = jax.device_put(state, repl)
    times = []
    it = minibatches(ds, bs_global, num_epoch=2)
    first = next(it)
    sharded = {k: jax.device_put(v, batch_sh) for k, v in first.items()}
    state, mm = step_fn(state, sharded)  # compile
    jax.block_until_ready(mm["loss"])
    for i, b in enumerate(it):
        if i >= args.steps:
            break
        t1 = time.perf_counter()
        sharded = {k: jax.device_put(v, batch_sh) for k, v in b.items()}
        state, mm = step_fn(state, sharded)
        jax.block_until_ready(mm["loss"])
        times.append(time.perf_counter() - t1)

    sync_mean = statistics.fmean(times)
    sync_var = statistics.pvariance(times)
    sync_cv = (sync_var**0.5) / sync_mean

    print(json.dumps({
        "metric": "ps_vs_allreduce_step_time",
        "protocol": args.protocol,
        "sync_allreduce": {
            "mean_s": round(sync_mean, 6),
            "var_s2": round(sync_var, 9),
            "cv": round(sync_cv, 4),
            "devices": ndev,
        },
        "async_ps": {
            "effective_step_mean_s": round(async_mean, 6),
            "steady_state_step_s": round(async_steady, 6),
            "vs_sync": round(async_steady / sync_mean, 2),
            "workers": args.workers,
            "commits": async_trainer.parameter_server.num_commits,
        },
        "note": "sync path is the recommended TPU default; cv is the "
                "jitter headline (lower is better)",
    }))


if __name__ == "__main__":
    main()
