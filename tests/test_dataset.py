import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset


def test_construct_and_accessors():
    ds = Dataset.from_arrays(
        features=np.zeros((10, 4), np.float32), label=np.arange(10)
    )
    assert ds.num_rows == 10
    assert len(ds) == 10
    assert set(ds.columns) == {"features", "label"}
    assert "features" in ds
    assert ds["features"].shape == (10, 4)


def test_length_mismatch_raises():
    with pytest.raises(ValueError):
        Dataset.from_arrays(a=np.zeros(3), b=np.zeros(4))


def test_with_column_is_functional():
    ds = Dataset.from_arrays(a=np.arange(5))
    ds2 = ds.with_column("b", np.arange(5) * 2)
    assert "b" not in ds
    assert np.array_equal(ds2["b"], np.arange(5) * 2)


def test_partitions_cover_all_rows():
    ds = Dataset.from_arrays(a=np.arange(103))
    parts = ds.partitions(8)
    assert len(parts) == 8
    total = np.concatenate([p["a"] for p in parts])
    assert np.array_equal(np.sort(total), np.arange(103))
    sizes = [len(p) for p in parts]
    assert max(sizes) - min(sizes) <= 1


def test_shuffle_is_permutation_and_deterministic():
    ds = Dataset.from_arrays(a=np.arange(50), b=np.arange(50) * 10)
    s1, s2 = ds.shuffle(seed=7), ds.shuffle(seed=7)
    assert np.array_equal(s1["a"], s2["a"])
    assert not np.array_equal(s1["a"], ds["a"])
    assert np.array_equal(np.sort(s1["a"]), np.arange(50))
    # row alignment preserved across columns
    assert np.array_equal(s1["b"], s1["a"] * 10)


def test_split():
    ds = Dataset.from_arrays(a=np.arange(100))
    tr, te = ds.split(0.8, seed=1)
    assert len(tr) == 80 and len(te) == 20
    assert np.array_equal(np.sort(np.concatenate([tr["a"], te["a"]])), np.arange(100))


def test_gather_select_drop_slice():
    ds = Dataset.from_arrays(a=np.arange(10), b=np.arange(10) + 100)
    assert np.array_equal(ds.gather(np.array([3, 1]))["a"], [3, 1])
    assert ds.select("a").columns == ["a"]
    assert ds.drop("a").columns == ["b"]
    assert np.array_equal(ds.slice(2, 5)["a"], [2, 3, 4])


def test_from_csv(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("x1,x2,y\n1,2,0\n3,4,1\n5,6,0\n")
    ds = Dataset.from_csv(str(p), features=["x1", "x2"], label="y")
    assert ds["features"].shape == (3, 2)
    assert np.array_equal(ds["label"], [0, 1, 0])


def test_head_and_describe():
    ds = Dataset.from_arrays(a=np.arange(10, dtype=np.float32),
                             s=np.array(["x"] * 10))
    assert len(ds.head(3)) == 3
    d = ds.describe()
    assert "a" in d and "s" not in d
    assert d["a"]["min"] == 0.0 and d["a"]["max"] == 9.0


def test_missing_column_names_available():
    ds = Dataset.from_arrays(features=np.zeros(3), label=np.zeros(3))
    with pytest.raises(KeyError, match="available.*features"):
        ds["featuers"]


def test_npz_roundtrip(tmp_path):
    ds = Dataset.from_arrays(features=np.arange(12, dtype=np.float32).reshape(4, 3),
                             label=np.arange(4))
    p = str(tmp_path / "d.npz")
    ds.to_npz(p)
    back = Dataset.from_npz(p)
    assert set(back.columns) == {"features", "label"}
    np.testing.assert_array_equal(back["features"], ds["features"])
