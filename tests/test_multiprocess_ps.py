"""True cross-process PS traffic: a worker in a separate Python process
commits to the gRPC PS over the loopback socket — the single-host
simulation of the DCN plane (no thread-shared memory anywhere)."""

import subprocess
import sys
import textwrap

import numpy as np

from distkeras_tpu.parallel.protocols import ADAGProtocol
from distkeras_tpu.parallel.ps_grpc import GrpcParameterServer

WORKER = textwrap.dedent(
    """
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distkeras_tpu.parallel.ps_grpc import GrpcClient

    port = int(sys.argv[1])
    c = GrpcClient("127.0.0.1", port)
    center, n = c.pull()
    assert np.allclose(center["w"], 0.0), center
    for i in range(10):
        c.commit({"delta": {"w": np.ones(4, np.float32)}, "commit_id": f"sub:{i}"})
    # replayed commit must dedupe server-side
    c.commit({"delta": {"w": np.ones(4, np.float32)}, "commit_id": "sub:0"})
    center, n = c.pull()
    print("WORKER_OK", n, float(center["w"][0]))
    """
)


def test_worker_subprocess_commits_over_grpc():
    ps = GrpcParameterServer(
        ADAGProtocol(), {"w": np.zeros(4, np.float32)}, num_workers=2, port=0
    )
    port = ps.start()
    try:
        r = subprocess.run(
            [sys.executable, "-c", WORKER, str(port)],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        assert "WORKER_OK 10" in r.stdout
        assert ps.service.num_commits == 10
        assert ps.service.num_duplicates == 1
        # ADAG: 10 * 1/2 = 5
        assert np.allclose(ps.get_model()["w"], 5.0)
    finally:
        ps.stop()
