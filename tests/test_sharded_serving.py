"""GSPMD tensor-parallel serving: sharded engine vs unsharded parity.

The sharded-serving contract under test, all on the suite's forced
virtual CPU devices (the CI variant re-runs this file at a different
forced count — tests read ``len(jax.devices())``, never assume 8):

- a ``serving_mesh`` engine is **token-identical** to the unsharded
  ``generate()`` reference on greedy decode — dense, paged
  (preempt/resume included), chunked + prefix-cached, and speculative
  modes — at tp=2 and tp=4;
- **compile-once survives the mesh**: every callable (decode, draft,
  verify) stays at exactly one executable under an armed
  ``RecompileAuditor``, explicit in/out shardings and all;
- params and KV leaves are REALLY sharded (NamedSharding carrying
  ``tp``), block tables and slot state stay replicated host metadata;
- a hot param swap places candidates shard-then-place into the SAME
  layout (no retrace, provenance flips, new-weight parity);
- a sharded 2-replica cluster rolls a reload through the router with
  zero client errors and per-replica ``(version, digest)`` flips;
- bad meshes and non-divisible models fail typed at construction;
- per-device memory attribution: a sharded engine's params/KV bytes are
  published per mesh device.
"""

import asyncio
import time

import jax
import numpy as np
import pytest

from distkeras_tpu.inference.generate import generate
from distkeras_tpu.models.bert import gpt_tiny
from distkeras_tpu.parallel.mesh import parse_mesh_shape, serving_mesh
from distkeras_tpu.serving import ServingEngine
from distkeras_tpu.telemetry import RecompileAuditor

VOCAB = 64

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="sharded serving needs >= 2 (virtual) devices")


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=64, vocab_size=VOCAB)
    return model, model.init(0)


@pytest.fixture(scope="module")
def mesh2():
    return serving_mesh({"tp": 2}, devices=jax.devices()[:2])


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


def _want(lm_pair, prompt, n, variables=None):
    model, default_vars = lm_pair
    return generate(model, variables or default_vars,
                    np.asarray([prompt], np.int32), n,
                    greedy=True)[0].tolist()


async def _run_engine(engine, coro):
    task = asyncio.create_task(engine.run())
    try:
        return await coro
    finally:
        engine.shutdown(drain=True)
        await task


def _tp_specs(tree):
    """The set of PartitionSpec strings across a pytree's leaves."""
    return {str(getattr(leaf.sharding, "spec", leaf.sharding))
            for leaf in jax.tree.leaves(tree)}


# -- validation ---------------------------------------------------------------

def test_mesh_shape_parsing_and_validation():
    assert parse_mesh_shape("tp=2") == {"tp": 2}
    assert parse_mesh_shape("4") == {"tp": 4}
    assert parse_mesh_shape("tp=2,dp=1") == {"tp": 2, "dp": 1}
    for bad in ("", "tp", "tp=x", "tp=0", "tp=2,tp=4"):
        with pytest.raises(ValueError):
            parse_mesh_shape(bad)
    n = len(jax.devices())
    # A product that does not divide the visible device count is a typed
    # error, not a deep jax traceback.
    with pytest.raises(ValueError, match="divide"):
        serving_mesh({"tp": n + 1})
    if n % 3:
        with pytest.raises(ValueError, match="divide"):
            serving_mesh({"tp": 3})
    with pytest.raises(ValueError, match="tp"):
        serving_mesh({"dp": 1})
    if n >= 4:
        # dp>1 inside one serving replica is rejected AT THE MESH (the
        # CLI layer), not only by the engine ctor — `cluster` must fail
        # one typed line, never spawn N crash-looping children.
        with pytest.raises(ValueError, match="replicas"):
            serving_mesh({"tp": 2, "dp": 2})
    # Default: one big tp replica over everything visible.
    assert dict(serving_mesh().shape) == {"tp": n}


def test_engine_rejects_unshardable_configs(lm, mesh2):
    model, variables = lm
    # vocab 65 does not divide tp=2 -> typed, names the offender.
    odd = gpt_tiny(seq_len=64, vocab_size=65)
    with pytest.raises(ValueError, match="vocab_size"):
        ServingEngine(odd, odd.init(0), slots=2, mesh=mesh2)
    # A serving mesh must carry tp; dp>1 inside ONE engine is rejected
    # (data parallelism in serving is N replicas).
    from distkeras_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="tp"):
        ServingEngine(model, variables, slots=2,
                      mesh=make_mesh({"dp": 2},
                                     devices=jax.devices()[:2]))
    if len(jax.devices()) >= 4:
        dp_mesh = make_mesh({"dp": 2, "tp": 2},
                            devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="replicas"):
            ServingEngine(model, variables, slots=2, mesh=dp_mesh)


# -- parity: dense / paged / chunked+cached / speculative ---------------------

def test_sharded_dense_greedy_parity_compile_once(lm, mesh2, rng):
    model, variables = lm
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=4, max_queue=16,
                           mesh=mesh2, auditor=auditor,
                           arm_auditor_after_warmup=True)
    # Params and KV really sharded; sampling state replicated.
    assert any("'tp'" in s for s in _tp_specs(engine._params))
    assert any("'tp'" in s for s in _tp_specs(engine._cache))
    assert _tp_specs(engine._tokens) == {"PartitionSpec()"}
    prompts = [_prompt(rng, n) for n in (3, 5, 8, 13, 6, 4, 9, 7)]

    async def work():
        reqs = [engine.submit(p, 8) for p in prompts]
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(engine, work()))
    assert outs == [_want(lm, p, 8) for p in prompts]
    assert auditor.compiles("serving_decode") == 1
    assert engine.mesh_info()["tp"] == 2
    assert len(engine.mesh_info()["devices"]) == 2


def test_sharded_prefix_cache_chunked_parity(lm, mesh2, rng):
    """Dense sharded engine with the device prefix cache AND chunked
    prefill: hits splice head-sharded pool rows, tails chunk through
    the sharded prefill — output still token-identical."""
    model, variables = lm
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=2, max_queue=16,
                           mesh=mesh2, prefix_cache_mb=4.0,
                           prefix_block_tokens=8, prefill_chunk=8,
                           auditor=auditor, arm_auditor_after_warmup=True)
    assert any("'tp'" in s for s in _tp_specs(engine.prefix_cache._pool))
    shared = _prompt(rng, 16)
    prompts = [shared + _prompt(rng, 4) for _ in range(4)]

    async def work():
        # Sequential: the 2nd+ requests hit the 1st's inserted blocks.
        outs = []
        for p in prompts:
            outs.append(await engine.submit(p, 6).result())
        return outs

    outs = asyncio.run(_run_engine(engine, work()))
    assert outs == [_want(lm, p, 6) for p in prompts]
    assert engine.prefix_cache.hit_tokens > 0, "no prefix hit exercised"
    assert auditor.compiles("serving_decode") == 1


def test_sharded_paged_preempt_resume_parity(lm, mesh2, rng):
    """Paged sharded engine with a pool tight enough to force
    preemption: preempt -> adopt -> requeue -> resume stays
    token-identical on a HEADS-SHARDED pool, tables stay host
    metadata, and the armed auditor holds compile-once throughout."""
    model, variables = lm
    auditor = RecompileAuditor()
    tight = ServingEngine(model, variables, slots=4, max_queue=16,
                          mesh=mesh2, kv_pool_blocks=13,
                          kv_block_tokens=4, auditor=auditor,
                          arm_auditor_after_warmup=True)
    assert any("'tp'" in s for s in _tp_specs(tight._cache))
    assert isinstance(tight._tables, np.ndarray)  # replicated host state
    prompts = [_prompt(rng, 12) for _ in range(4)]

    async def work():
        reqs = [tight.submit(p, 10) for p in prompts]
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(tight, work()))
    assert outs == [_want(lm, p, 10) for p in prompts]
    assert tight.metrics.preemptions > 0, (
        "pool was supposed to be tight enough to force preemption")
    assert auditor.compiles("serving_decode") == 1


def test_sharded_speculative_parity_compile_once(lm, mesh2, rng):
    """Speculative sharded engine (draft==target, replicated draft on a
    sharded target over one paged pool): greedy rows commit draft
    prefixes, a sampled row and an opt-out greedy row ride the same
    batch, everything token-identical, and decode/draft/verify each
    stay at ONE executable."""
    model, variables = lm
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=2, max_queue=16,
                           mesh=mesh2, kv_pool_mb=1.0,
                           draft_model=model, draft_variables=variables,
                           spec_k=4, auditor=auditor,
                           arm_auditor_after_warmup=True)
    # The draft is replicated: no tp axis anywhere in its state.
    assert not any("'tp'" in s for s in _tp_specs(engine._draft_params))
    prompts = [_prompt(rng, n) for n in (3, 6, 9, 5)]

    async def work():
        greedy = [engine.submit(p, 8) for p in prompts]
        optout = engine.submit(prompts[0], 8, speculate=False)
        sampled = engine.submit(prompts[1], 8, temperature=0.8)
        outs = [await r.result() for r in greedy]
        return outs, await optout.result(), await sampled.result()

    outs, optout, sampled = asyncio.run(_run_engine(engine, work()))
    want = [_want(lm, p, 8) for p in prompts]
    assert outs == want
    assert optout == want[0]
    assert len(sampled) == 8
    assert engine.metrics.spec_accepted_tokens > 0
    compiles = {n: auditor.compiles(n)
                for n in ("serving_decode", "serving_draft",
                          "serving_verify")}
    assert compiles == {"serving_decode": 1, "serving_draft": 1,
                        "serving_verify": 1}, compiles


@pytest.mark.skipif(len(jax.devices()) < 4,
                    reason="tp=4 needs >= 4 devices")
def test_tp4_paged_parity(lm, rng):
    model, variables = lm
    mesh4 = serving_mesh({"tp": 4}, devices=jax.devices()[:4])
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=2, max_queue=16,
                           mesh=mesh4, kv_pool_mb=1.0, auditor=auditor,
                           arm_auditor_after_warmup=True)
    prompts = [_prompt(rng, n) for n in (4, 7, 11)]

    async def work():
        reqs = [engine.submit(p, 8) for p in prompts]
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(engine, work()))
    assert outs == [_want(lm, p, 8) for p in prompts]
    assert auditor.compiles("serving_decode") == 1
    assert engine.mesh_info()["axes"]["tp"] == 4


# -- hot swap: shard-then-place -----------------------------------------------

def test_sharded_param_swap_no_retrace(lm, mesh2, rng):
    """request_param_swap on a sharded engine: the candidate is placed
    straight into its mesh layout (post-swap params still carry tp),
    provenance flips, the armed auditor proves the swap-rewarm did not
    retrace, and post-swap output matches generate() under the NEW
    weights."""
    model, variables = lm
    new_vars = model.init(7)
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=2, max_queue=16,
                           mesh=mesh2, auditor=auditor,
                           arm_auditor_after_warmup=True)
    p = _prompt(rng, 6)

    async def work():
        before = await engine.submit(p, 6).result()
        ev, res = engine.request_param_swap(
            new_vars, provenance={"version": 9, "digest": "d9"})
        await asyncio.wait_for(ev.wait(), 60)
        assert res.get("ok"), res
        after = await engine.submit(p, 6).result()
        return before, after

    before, after = asyncio.run(_run_engine(engine, work()))
    assert before == _want(lm, p, 6)
    assert after == _want(lm, p, 6, variables=new_vars)
    assert engine.weight_version == {"version": 9, "digest": "d9"}
    assert any("'tp'" in s for s in _tp_specs(engine._params)), (
        "swap dropped the params' tp layout")
    assert auditor.compiles("serving_decode") == 1


# -- sharded fleet: rolling reload --------------------------------------------

def test_sharded_rolling_reload_zero_errors(lm, mesh2, rng, tmp_path):
    """Two SHARDED LocalReplicas behind the router: a rolling reload
    under continuous client load flips every replica's (version,
    digest) with zero client-visible errors; fleet healthz rolls up a
    single version and a consistent mesh per replica."""
    from distkeras_tpu.checkpoint import save_weights_file, \
        weights_provenance
    from distkeras_tpu.serving import (
        LocalReplica, ServingClient, ServingCluster,
    )
    from distkeras_tpu.telemetry import MetricsRegistry

    model, variables = lm
    new_vars = model.init(3)
    weights_path = str(tmp_path / "w2.bin")
    save_weights_file(weights_path, new_vars)
    pool = [_prompt(rng, n) for n in (4, 6, 5)]

    engines = {}

    def factory(i):
        def build():
            eng = ServingEngine(model, variables, slots=2, max_queue=16,
                                mesh=mesh2,
                                auditor=RecompileAuditor(),
                                arm_auditor_after_warmup=True)
            engines[i] = eng
            return eng

        return LocalReplica(build)

    async def go():
        cluster = ServingCluster(
            factory, 2, registry=MetricsRegistry(),
            supervisor_kwargs=dict(health_interval_s=0.05,
                                   base_delay_s=0.05))
        completions = []
        stop = asyncio.Event()

        async def worker(k):
            async with ServingClient("127.0.0.1", cluster.port) as c:
                while not stop.is_set():
                    prompt = pool[(k + len(completions)) % len(pool)]
                    done = await c.generate(prompt, 5)
                    completions.append(
                        (time.monotonic(), tuple(prompt), done["tokens"],
                         done.get("weight_version")))

        async with cluster:
            workers = [asyncio.create_task(worker(k)) for k in range(3)]
            deadline = time.monotonic() + 60
            while len(completions) < 4:
                assert time.monotonic() < deadline
                await asyncio.sleep(0.02)
            async with ServingClient("127.0.0.1", cluster.port) as c:
                rep = await c.reload(weights_path, timeout=120.0)
            t1 = time.monotonic()
            n_after = len(completions) + 4
            while len(completions) < n_after:
                assert time.monotonic() < deadline + 60
                await asyncio.sleep(0.02)
            stop.set()
            await asyncio.gather(*workers)
            async with ServingClient("127.0.0.1", cluster.port) as c:
                health = await c.healthz()
        return rep, completions, t1, health

    rep, completions, t1, health = asyncio.run(go())
    assert rep["ok"] and sorted(rep["reloaded"]) == ["r0", "r1"]
    assert rep["failed"] == {}
    prov = weights_provenance(weights_path)
    key = f"{prov['version']}:{prov['digest']}"
    # Per-replica flip, rolled up at the router; meshes consistent.
    assert health["router"]["weight_versions"] == {key: 2}
    assert health["router"]["mixed_weight_versions"] is False
    for rid, entry in health["replicas"].items():
        sub = entry.get("healthz") or {}
        assert sub.get("mesh", {}).get("axes", {}).get("tp") == 2, (
            rid, sub.get("mesh"))
    # Zero client errors (a worker exception would have propagated) and
    # post-roll parity on the new weights.
    want_new = {tuple(p): _want(lm, p, 5, variables=new_vars)
                for p in pool}
    post = [c for c in completions if c[0] > t1]
    assert post, "no completion landed after the roll"
    for _, p, got, wv in post:
        assert got == want_new[p]
        assert wv["version"] == prov["version"]
        assert wv["digest"] == prov["digest"]
    for i, eng in engines.items():
        assert eng.auditor.compiles("serving_decode") == 1, f"replica {i}"


# -- observability ------------------------------------------------------------

def test_sharded_memory_attribution(lm, mesh2):
    """refresh_memory_metrics on a sharded engine: params/KV bytes are
    attributed per mesh device — healthz rows carry per-device
    params_bytes/kv_bytes, and the registry grows device-labeled
    model_params_bytes / kv_pool_reserved_bytes series."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=2, mesh=mesh2,
                           kv_pool_mb=1.0)
    rows = engine.refresh_memory_metrics()
    mesh_devs = set(engine.mesh_info()["devices"])
    by_dev = {r["device"]: r for r in rows if r["device"] in mesh_devs}
    assert set(by_dev) == mesh_devs
    for r in by_dev.values():
        assert r.get("params_bytes", 0) > 0
        assert r.get("kv_bytes", 0) > 0
    # The sharded halves of the pool really are halves: KV per device
    # is strictly less than the whole pool's bytes.
    total_kv = engine.kv_pool.capacity * engine.kv_pool.bytes_per_block
    for r in by_dev.values():
        assert r["kv_bytes"] < total_kv
    snap = engine.metrics.registry.snapshot()
    labeled = [k for k in snap
               if k.startswith("model_params_bytes{") and "device=" in k]
    assert len(labeled) >= 2, sorted(snap)[:40]


def test_healthz_mesh_info_unsharded_absent(lm):
    model, variables = lm
    engine = ServingEngine(model, variables, slots=2)
    assert engine.mesh_info() is None
    assert "mesh" not in engine.debugz()


# -- e2e: a real `run.py serve --mesh` child process --------------------------

@pytest.mark.slow
def test_serve_mesh_e2e_child_process(rng):
    """`run.py serve --mesh-shape tp=2 --force-host-devices 2` as a real
    child: the banner names the mesh, a TCP stream is token-identical
    to the parent's (unsharded) generate(), and healthz carries the
    mesh plus per-device params/KV attribution."""
    import json
    import os
    import signal
    import subprocess
    import sys

    from distkeras_tpu.serving import ServingClient

    child = subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.run", "serve",
         "--model", "gpt_tiny", "--port", "0",
         "--mesh-shape", "tp=2", "--force-host-devices", "2",
         "--kv-pool-mb", "4", "--audit-recompiles", "arm"],
        stdout=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        line = child.stdout.readline()
        assert line, "serve child exited before its banner"
        banner = json.loads(line)
        assert banner["mesh"]["axes"]["tp"] == 2
        assert len(banner["mesh"]["devices"]) == 2
        port = banner["port"]
        model = gpt_tiny()
        variables = model.init(0)
        prompt = _prompt(rng, 7)
        want = generate(model, variables, np.asarray([prompt], np.int32),
                        8, greedy=True)[0].tolist()

        async def go():
            async with ServingClient("127.0.0.1", port) as c:
                done = await c.generate(prompt, 8)
                health = await c.healthz()
            return done, health

        done, health = asyncio.run(go())
        assert done["tokens"] == want, "sharded child diverged"
        assert health["mesh"]["axes"]["tp"] == 2
        per_dev = [r for r in health["device_memory"]
                   if r.get("params_bytes")]
        assert len(per_dev) == 2, health["device_memory"]
    finally:
        child.send_signal(signal.SIGTERM)
        try:
            child.wait(timeout=30)
        except subprocess.TimeoutExpired:
            child.kill()
