"""Pipeline-parallel serving: pp-meshed engine vs unsharded parity.

The pp-serving contract under test, all on the suite's forced virtual
CPU devices (the CI ``tier1-pp-4dev`` variant re-runs this file at a
different forced count — tests read ``len(jax.devices())``, never
assume 8):

- a ``tp=1,pp=2`` engine is **token-identical** to the unsharded
  ``generate()`` reference on greedy decode — dense, paged
  (preempt/resume included), chunked + prefix-cached, and speculative
  modes;
- **compile-once survives the stage split**: every per-stage callable
  stays at exactly one executable under an armed ``RecompileAuditor``;
- per-stage placement is REAL: stage s's params and KV leaves live only
  on stage s's devices, boot and after a hot swap alike;
- ``pipeline_depth>=pp`` micro-batching streams the SAME tokens as
  depth 0 and records a ``bubble_fraction``;
- a pp+tp combined mesh (device-gated) keeps all of the above;
- bad stage plans and bad depths fail typed at construction;
- mesh_info/debugz/healthz carry the pp axis: per-stage device lists,
  per-stage params/KV bytes, a ``stages:`` line on the pretty page.
"""

import asyncio
import json

import jax
import numpy as np
import pytest

from distkeras_tpu.inference.generate import generate
from distkeras_tpu.models.bert import gpt_tiny
from distkeras_tpu.parallel.mesh import serving_mesh
from distkeras_tpu.parallel.pp import plan_stages
from distkeras_tpu.serving import ServingEngine
from distkeras_tpu.telemetry import RecompileAuditor

VOCAB = 64

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="pipeline-parallel serving needs >= 2 (virtual) devices")


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=64, vocab_size=VOCAB)
    return model, model.init(0)


@pytest.fixture(scope="module")
def pp2():
    return serving_mesh({"tp": 1, "pp": 2}, devices=jax.devices()[:2])


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


def _want(lm_pair, prompt, n, variables=None):
    model, default_vars = lm_pair
    return generate(model, variables or default_vars,
                    np.asarray([prompt], np.int32), n,
                    greedy=True)[0].tolist()


async def _run_engine(engine, coro):
    task = asyncio.create_task(engine.run())
    try:
        return await coro
    finally:
        engine.shutdown(drain=True)
        await task


def _stage_device_sets(engine):
    return [set(m.devices.flatten()) for m in engine._stage_meshes]


def _assert_stage_placement(engine, trees):
    """Every leaf of per-stage subtree s must reside ONLY on stage s's
    devices — the whole point of pp placement."""
    stage_devs = _stage_device_sets(engine)
    for s, tree in enumerate(trees):
        for leaf in jax.tree.leaves(tree):
            assert set(leaf.devices()) <= stage_devs[s], (
                f"stage {s} leaf leaked onto foreign devices: "
                f"{leaf.devices()} vs {stage_devs[s]}")


def _stage_compiles(auditor, pp, name="serving_decode"):
    return [auditor.compiles(f"{name}_s{s}") for s in range(pp)]


# -- validation ---------------------------------------------------------------

def test_stage_plan_validation():
    plan = plan_stages(4, 2)
    assert plan.layers_per_stage == 2
    assert plan.layer_range(0) == (0, 2) and plan.layer_range(1) == (2, 4)
    assert plan.stage_arg(0) == (0, 2, True, False)
    assert plan.stage_arg(1) == (2, 4, False, True)
    # token_embed is placed on BOTH ends (tied head reads it back).
    assert plan.owner_stages("token_embed") == (0, 1)
    assert plan.owner_stages("pos_embed") == (0,)
    assert plan.owner_stages("ln_final") == (1,)
    with pytest.raises(ValueError, match="pp=0"):
        plan_stages(4, 0)
    with pytest.raises(ValueError, match="at least one layer"):
        plan_stages(2, 4)
    with pytest.raises(ValueError, match="divide"):
        plan_stages(3, 2)


def test_engine_rejects_bad_depth_and_unsplittable_model(lm, pp2):
    model, variables = lm
    # Depth > 1 without a pp mesh is a typed error, not a hang.
    with pytest.raises(ValueError, match="pipeline_depth"):
        ServingEngine(model, variables, slots=4, pipeline_depth=2)
    with pytest.raises(ValueError, match="pipeline_depth"):
        ServingEngine(model, variables, slots=4, mesh=pp2,
                      pipeline_depth=-1)
    # Slots must divide into equal micro-batches.
    with pytest.raises(ValueError, match="micro-batch"):
        ServingEngine(model, variables, slots=3, mesh=pp2,
                      pipeline_depth=2)
    # gpt_tiny has 2 layers: a 2-device pp=2 mesh splits 1+1; a model
    # whose layer count does not divide pp fails typed at construction.
    from distkeras_tpu.models.bert import BertConfig, _make

    cfg3 = BertConfig(vocab_size=VOCAB, hidden_size=32, num_layers=3,
                      num_heads=2, mlp_dim=64, max_seq_len=64,
                      causal=True)
    odd = _make(cfg3, 64, "gpt_3layer")
    with pytest.raises(ValueError, match="divide"):
        ServingEngine(odd, odd.init(0), slots=2, mesh=pp2)
    # Speculative decoding runs verify over the whole slot batch —
    # micro-batched depth is rejected, not silently ignored.
    with pytest.raises(ValueError, match="pipeline_depth"):
        ServingEngine(model, variables, slots=4, mesh=pp2,
                      kv_pool_mb=1.0, draft_model=model,
                      draft_variables=variables, spec_k=3,
                      pipeline_depth=2)


# -- parity: dense / paged / chunked+cached / speculative ---------------------

def test_pp_dense_greedy_parity_compile_once_per_stage(lm, pp2, rng):
    model, variables = lm
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=4, max_queue=16,
                           mesh=pp2, auditor=auditor,
                           arm_auditor_after_warmup=True)
    # Per-stage placement of params AND the dense KV cache (per
    # micro-batch cache trees all stage-local).
    _assert_stage_placement(engine, engine._params)
    for mb_caches in zip(*engine._cache):
        _assert_stage_placement(engine, list(mb_caches))
    prompts = [_prompt(rng, n) for n in (3, 5, 8, 13, 6, 4, 9, 7)]

    async def work():
        reqs = [engine.submit(p, 8) for p in prompts]
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(engine, work()))
    assert outs == [_want(lm, p, 8) for p in prompts]
    assert _stage_compiles(auditor, 2) == [1, 1]


def test_pp_paged_preempt_resume_parity(lm, pp2, rng):
    """Paged pp engine with a pool tight enough to force preemption:
    preempt -> adopt -> requeue -> resume stays token-identical on
    stage-partitioned pools, and every stage holds compile-once."""
    model, variables = lm
    auditor = RecompileAuditor()
    tight = ServingEngine(model, variables, slots=4, max_queue=16,
                          mesh=pp2, kv_pool_blocks=13,
                          kv_block_tokens=4, auditor=auditor,
                          arm_auditor_after_warmup=True)
    _assert_stage_placement(tight, tight._params)
    _assert_stage_placement(tight, tight._cache)
    assert isinstance(tight._tables, np.ndarray)  # replicated host state
    prompts = [_prompt(rng, 12) for _ in range(4)]

    async def work():
        reqs = [tight.submit(p, 10) for p in prompts]
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(tight, work()))
    assert outs == [_want(lm, p, 10) for p in prompts]
    assert tight.metrics.preemptions > 0, (
        "pool was supposed to be tight enough to force preemption")
    assert _stage_compiles(auditor, 2) == [1, 1]


def test_pp_prefix_cache_chunked_parity(lm, pp2, rng):
    """pp engine with the device prefix cache AND chunked prefill: hits
    splice per-stage pool rows, tails chunk through the staged prefill
    — output still token-identical, one trie spanning all stages."""
    model, variables = lm
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=2, max_queue=16,
                           mesh=pp2, prefix_cache_mb=4.0,
                           prefix_block_tokens=8, prefill_chunk=8,
                           auditor=auditor,
                           arm_auditor_after_warmup=True)
    # The prefix cache's pool is per-stage, each stage-local.
    _assert_stage_placement(engine, engine.prefix_cache._pool)
    shared = _prompt(rng, 16)
    prompts = [shared + _prompt(rng, 4) for _ in range(4)]

    async def work():
        outs = []
        for p in prompts:
            outs.append(await engine.submit(p, 6).result())
        return outs

    outs = asyncio.run(_run_engine(engine, work()))
    assert outs == [_want(lm, p, 6) for p in prompts]
    assert engine.prefix_cache.hit_tokens > 0, "no prefix hit exercised"
    assert _stage_compiles(auditor, 2) == [1, 1]


def test_pp_speculative_parity_compile_once(lm, pp2, rng):
    """Speculative pp engine (replicated draft, staged verify over one
    stage-partitioned paged pool): greedy rows commit draft prefixes,
    opt-out and sampled rows ride the same batch, everything
    token-identical, every staged verify at ONE executable."""
    model, variables = lm
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=2, max_queue=16,
                           mesh=pp2, kv_pool_mb=1.0,
                           draft_model=model, draft_variables=variables,
                           spec_k=4, auditor=auditor,
                           arm_auditor_after_warmup=True)
    prompts = [_prompt(rng, n) for n in (3, 6, 9, 5)]

    async def work():
        greedy = [engine.submit(p, 8) for p in prompts]
        optout = engine.submit(prompts[0], 8, speculate=False)
        sampled = engine.submit(prompts[1], 8, temperature=0.8)
        outs = [await r.result() for r in greedy]
        return outs, await optout.result(), await sampled.result()

    outs, optout, sampled = asyncio.run(_run_engine(engine, work()))
    want = [_want(lm, p, 8) for p in prompts]
    assert outs == want
    assert optout == want[0]
    assert len(sampled) == 8
    assert engine.metrics.spec_accepted_tokens > 0
    assert _stage_compiles(auditor, 2, "serving_verify") == [1, 1]
    assert auditor.compiles("serving_draft") == 1


# -- depth > 1: micro-batched overlap -----------------------------------------

def test_pp_depth_identical_tokens_and_bubble_metric(lm, pp2, rng):
    """``pipeline_depth>=pp`` micro-batching is pure overlap: the SAME
    greedy tokens as the serialized depth-0 engine, per-stage
    compile-once, and a recorded ``bubble_fraction``."""
    model, variables = lm
    prompts = [_prompt(rng, n) for n in (3, 5, 8, 13, 6, 4, 9, 7)]
    by_depth = {}
    for depth in (0, 2):
        auditor = RecompileAuditor()
        engine = ServingEngine(model, variables, slots=4, max_queue=16,
                               mesh=pp2, pipeline_depth=depth,
                               auditor=auditor,
                               arm_auditor_after_warmup=True)

        async def work(engine=engine):
            reqs = [engine.submit(p, 8) for p in prompts]
            return [await r.result() for r in reqs]

        by_depth[depth] = asyncio.run(_run_engine(engine, work()))
        assert _stage_compiles(auditor, 2) == [1, 1], depth
        if depth >= 2:
            assert engine._mb_count == depth
            frac = engine.metrics.bubble.fraction
            assert frac is not None and 0.0 <= frac <= 1.0
            assert "bubble_fraction" in engine.metrics.summary()
    assert by_depth[0] == by_depth[2] == [
        _want(lm, p, 8) for p in prompts]


def test_pp_paged_depth_preempt_mid_microbatch_parity(lm, pp2, rng):
    """Depth-2 micro-batched PAGED decode under a pool tight enough to
    preempt mid-flight: a slot preempted in one micro-batch resumes
    (possibly in another tick) token-identical, stages stay at one
    executable."""
    model, variables = lm
    auditor = RecompileAuditor()
    tight = ServingEngine(model, variables, slots=4, max_queue=16,
                          mesh=pp2, pipeline_depth=2,
                          kv_pool_blocks=13, kv_block_tokens=4,
                          auditor=auditor, arm_auditor_after_warmup=True)
    prompts = [_prompt(rng, 12) for _ in range(6)]

    async def work():
        reqs = [tight.submit(p, 10) for p in prompts]
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(tight, work()))
    assert outs == [_want(lm, p, 10) for p in prompts]
    assert tight.metrics.preemptions > 0, (
        "pool was supposed to be tight enough to force preemption")
    assert _stage_compiles(auditor, 2) == [1, 1]


# -- hot swap: shard-then-place per stage -------------------------------------

def test_pp_param_swap_no_retrace(lm, pp2, rng):
    """request_param_swap on a pp engine: the candidate is split and
    placed straight into each stage's layout (post-swap leaves still
    stage-local), the armed auditor proves no stage retraced, and
    post-swap output matches generate() under the NEW weights."""
    model, variables = lm
    new_vars = model.init(7)
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=2, max_queue=16,
                           mesh=pp2, auditor=auditor,
                           arm_auditor_after_warmup=True)
    p = _prompt(rng, 6)

    async def work():
        before = await engine.submit(p, 6).result()
        ev, res = engine.request_param_swap(
            new_vars, provenance={"version": 9, "digest": "d9"})
        await asyncio.wait_for(ev.wait(), 60)
        assert res.get("ok"), res
        after = await engine.submit(p, 6).result()
        return before, after

    before, after = asyncio.run(_run_engine(engine, work()))
    assert before == _want(lm, p, 6)
    assert after == _want(lm, p, 6, variables=new_vars)
    assert engine.weight_version == {"version": 9, "digest": "d9"}
    _assert_stage_placement(engine, engine._params)
    assert _stage_compiles(auditor, 2) == [1, 1]


# -- pp + tp combined ---------------------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 4,
                    reason="tp=2,pp=2 needs >= 4 devices")
def test_pp_tp_combined_parity(lm, rng):
    """tp=2,pp=2 on 4 devices: params tp-sharded WITHIN each stage,
    stages device-disjoint, greedy output still token-identical with
    per-stage compile-once — the full second-axis claim."""
    model, variables = lm
    mesh = serving_mesh({"tp": 2, "pp": 2}, devices=jax.devices()[:4])
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=4, max_queue=16,
                           mesh=mesh, kv_pool_mb=1.0,
                           pipeline_depth=2, auditor=auditor,
                           arm_auditor_after_warmup=True)
    _assert_stage_placement(engine, engine._params)
    stage_devs = _stage_device_sets(engine)
    assert not (stage_devs[0] & stage_devs[1]), "stages share devices"
    assert all(len(d) == 2 for d in stage_devs)
    # tp really shards within a stage: some param leaf spans BOTH of
    # its stage's devices.
    assert any(len(leaf.devices()) == 2
               for leaf in jax.tree.leaves(engine._params[0]))
    prompts = [_prompt(rng, n) for n in (4, 7, 11, 5)]

    async def work():
        reqs = [engine.submit(p, 8) for p in prompts]
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(engine, work()))
    assert outs == [_want(lm, p, 8) for p in prompts]
    assert _stage_compiles(auditor, 2) == [1, 1]
    info = engine.mesh_info()
    assert info["axes"] == {"tp": 2, "pp": 2}


# -- observability: mesh_info / debugz / healthz ------------------------------

def test_pp_mesh_info_debugz_healthz_stages(lm, pp2, rng):
    from distkeras_tpu.serving import ServingClient, ServingServer
    from distkeras_tpu.serving.debugz import format_debugz

    model, variables = lm
    engine = ServingEngine(model, variables, slots=2, max_queue=16,
                           mesh=pp2, pipeline_depth=2)

    async def go():
        server = ServingServer(engine, port=0)
        await server.start()
        async with ServingClient("127.0.0.1", server.port) as c:
            await c.generate(_prompt(rng, 6), 4)
            health = await c.healthz()
        await server.stop(drain=True)
        return health

    health = asyncio.run(go())
    # healthz: the pipeline block carries the pp axis + measured bubble.
    assert health["pipeline"]["stages"] == 2
    assert health["pipeline"]["micro_batches"] == 2
    assert "bubble_fraction" in health["pipeline"]
    # mesh_info (also embedded in healthz["mesh"]): per-stage devices,
    # layer ranges, and resident params/KV bytes.
    for info in (engine.mesh_info(), health["mesh"]):
        assert info["pp"] == 2
        stages = info["stages"]
        assert [st["stage"] for st in stages] == [0, 1]
        assert stages[0]["layers"] == [0, 1]
        assert stages[1]["layers"] == [1, 2]
        for st in stages:
            assert len(st["devices"]) == 1
            assert st["params_bytes"] > 0
            assert st["kv_bytes"] > 0
        assert set(stages[0]["devices"]).isdisjoint(stages[1]["devices"])
    # debugz: JSON-safe dict + a stages: line on the pretty page,
    # without breaking the existing pipeline: line format.
    dz = engine.debugz()
    json.dumps(dz)
    assert dz["pipeline"]["stages"] == 2
    assert dz["pipeline"]["micro_batches"] == 2
    page = format_debugz(dz)
    assert "pipeline: depth=2" in page
    assert "stages: 2 pp stage(s) x 2 micro-batch(es)" in page
