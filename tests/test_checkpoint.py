"""Checkpoint/resume tests (a capability the reference lacks — SURVEY §5)."""

import numpy as np
import pytest

from distkeras_tpu.checkpoint import CheckpointManager
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.ops.losses import get_optimizer
from distkeras_tpu.training.step import TrainState, make_train_step


def _state():
    model = Model.from_flax(MLP(features=(8,), num_classes=2), input_shape=(4,))
    opt = get_optimizer("adam", 1e-2)
    return model, opt, TrainState.create(model, opt, rng=0)


def test_save_restore_roundtrip(tmp_path):
    model, opt, state = _state()
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(0, state=state, meta={"note": "t"})
    assert mgr.latest_step() == 0
    restored = mgr.restore(0, like={"state": state, "meta": {"note": "t"}})
    w0 = state.params["Dense_0"]["kernel"]
    np.testing.assert_array_equal(
        np.asarray(restored["state"].params["Dense_0"]["kernel"]), np.asarray(w0)
    )
    mgr.close()


def test_resume_continues_training(tmp_path):
    model, opt, state = _state()
    step_fn = make_train_step(model, opt, "categorical_crossentropy", donate=False)
    rng = np.random.default_rng(0)
    batch = {
        "features": rng.normal(size=(16, 4)).astype(np.float32),
        "label": (rng.normal(size=(16,)) > 0).astype(np.float32),
    }
    for _ in range(3):
        state, _ = step_fn(state, batch)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(3, state=state, ps_center=state.params, ps_num_updates=7)
    restored = mgr.restore(
        3, like={"state": state, "ps": {"center": state.params, "num_updates": 0}}
    )
    assert int(restored["state"].step) == 3
    assert int(restored["ps"]["num_updates"]) == 7
    # resumed state steps forward identically to the uninterrupted one
    cont, _ = step_fn(restored["state"], batch)
    direct, _ = step_fn(state, batch)
    np.testing.assert_allclose(
        np.asarray(cont.params["Dense_0"]["kernel"]),
        np.asarray(direct.params["Dense_0"]["kernel"]),
        atol=1e-7,
    )
    mgr.close()


def test_max_to_keep(tmp_path):
    model, opt, state = _state()
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    for s in (0, 1, 2, 3):
        mgr.save(s, state=state)
    assert mgr.latest_step() == 3
    assert len(mgr.all_steps()) <= 2
    mgr.close()


def test_restore_into_sharded_template(tmp_path):
    """A checkpoint restores directly into a GSPMD-sharded TrainState: the
    template's shardings are honored, so params come back distributed."""
    import jax
    from distkeras_tpu.models.bert import bert_tiny_mlm
    from distkeras_tpu.parallel.gspmd import sharded_train_state
    from distkeras_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 2, "tp": 4})
    model = bert_tiny_mlm(seq_len=8, vocab_size=64)
    opt = get_optimizer("adam", 1e-3)
    state, _ = sharded_train_state(model, opt, mesh, rng=0)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(0, state=state)
    restored = mgr.restore(0, like={"state": state})["state"]
    k = restored.params["layer_0"]["mlp_in"]["kernel"]
    # sharding preserved: mlp dim split over tp=4
    assert {s.data.shape for s in k.addressable_shards} == {(128, 128)}
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(k)),
        np.asarray(jax.device_get(state.params["layer_0"]["mlp_in"]["kernel"])),
    )
    mgr.close()


def test_sync_trainer_checkpoint_resume_matches_uninterrupted(tmp_path, rng):
    """Interrupted-then-resumed sync training must reproduce the
    uninterrupted run: same batches (deterministic per-epoch stream skipped
    past the restored step), same optimizer state."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.mlp import mnist_mlp

    def _model():
        from distkeras_tpu.models.core import Model
        from distkeras_tpu.models.mlp import MLP

        return Model.from_flax(
            MLP(features=(16,), num_classes=4), input_shape=(8,)
        )

    x = np.asarray(rng.normal(size=(256, 8)), np.float32)
    y = np.asarray(rng.integers(0, 4, size=(256,)), np.int32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    kwargs = dict(worker_optimizer="adam", learning_rate=1e-2,
                  batch_size=8, seed=0)

    # A: uninterrupted 2 epochs.
    a = dk.SynchronousDistributedTrainer(_model(), num_epoch=2, **kwargs)
    trained_a = a.train(ds, shuffle=True)

    # B: 1 epoch with checkpointing; C: resume for the full 2-epoch stream.
    ck = str(tmp_path / "sync_ck")
    b = dk.SynchronousDistributedTrainer(
        _model(), num_epoch=1, checkpoint_dir=ck, **kwargs
    )
    b.train(ds, shuffle=True)
    c = dk.SynchronousDistributedTrainer(
        _model(), num_epoch=2, checkpoint_dir=ck, resume=True, **kwargs
    )
    trained_c = c.train(ds, shuffle=True)
    # C ran only the second epoch's steps.
    assert len(c.history) == len(a.history) - len(b.history)
    np.testing.assert_allclose(
        np.asarray(trained_c.params["Dense_0"]["kernel"]),
        np.asarray(trained_a.params["Dense_0"]["kernel"]),
        atol=1e-6, rtol=1e-6,
    )


@pytest.mark.slow
def test_pipeline_trainer_checkpoint_resume(tmp_path, rng):
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import BertConfig, _make

    vocab, seq = 32, 16
    cfg = BertConfig(
        vocab_size=vocab, hidden_size=32, num_layers=2, num_heads=2,
        mlp_dim=64, max_seq_len=seq, dropout_rate=0.0,
    )
    toks = np.asarray(rng.integers(0, vocab, size=(64, seq)), np.int32)
    ds = dk.Dataset.from_arrays(features=toks, label=toks)
    kwargs = dict(worker_optimizer="adam", learning_rate=1e-3,
                  num_stages=2, num_microbatches=2, batch_size=16, seed=0)

    a = dk.PipelineTrainer(_make(cfg, seq, "bp"), num_epoch=2, **kwargs)
    trained_a = a.train(ds)

    ck = str(tmp_path / "pp_ck")
    b = dk.PipelineTrainer(
        _make(cfg, seq, "bp"), num_epoch=1, checkpoint_dir=ck, **kwargs
    )
    b.train(ds)
    c = dk.PipelineTrainer(
        _make(cfg, seq, "bp"), num_epoch=2, checkpoint_dir=ck, resume=True,
        **kwargs
    )
    trained_c = c.train(ds)
    assert len(c.history) == len(a.history) - len(b.history)
    np.testing.assert_allclose(
        np.asarray(trained_c.params["layer_0"]["attention"]["query"]["kernel"]),
        np.asarray(trained_a.params["layer_0"]["attention"]["query"]["kernel"]),
        atol=1e-5, rtol=1e-5,
    )


def test_finalize_after_interval_save_same_step(tmp_path):
    """A zero checkpoint interval makes maybe_save persist the final step
    right before finalize sees it; finalize must drain the async write, not
    re-save (orbax raises StepAlreadyExists on a duplicate save)."""
    from distkeras_tpu.training.trainers import _StepCheckpointer

    _, _, state = _state()
    ck = _StepCheckpointer(str(tmp_path / "ck"), 0.0, False, like=state)
    for step in (1, 2, 3):
        ck.maybe_save(step, state)
    ck.finalize(3, state)  # same step maybe_save just saved
    ck.close()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr.latest_step() == 3
    mgr.close()


@pytest.mark.slow
def test_pipeline_1f1b_moe_ep_checkpoint_resume(tmp_path, rng):
    """Resume the round-5 flagship composition: schedule='1f1b' with an
    MoE trunk and P(\"pp\",\"ep\")-sharded expert leaves on a dp x pp x ep
    mesh — restored leaves must re-place onto their mesh shardings and the
    resumed run must land exactly where the uninterrupted run does."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import BertConfig, _make
    from distkeras_tpu.parallel.mesh import make_mesh

    vocab, seq = 32, 8
    cfg = BertConfig(
        vocab_size=vocab, hidden_size=16, num_layers=2, num_heads=2,
        mlp_dim=32, max_seq_len=seq, dropout_rate=0.0, moe_experts=4,
    )
    toks = np.asarray(rng.integers(0, vocab, size=(64, seq)), np.int32)
    ds = dk.Dataset.from_arrays(features=toks, label=toks)

    def trainer(name, **kw):
        mesh = make_mesh({"dp": 2, "pp": 2, "ep": 2})
        return dk.PipelineTrainer(
            _make(cfg, seq, name), worker_optimizer="adam",
            learning_rate=1e-3, num_stages=2, num_microbatches=2,
            batch_size=16, seed=0, schedule="1f1b", mesh=mesh, ep=2, **kw,
        )

    a = trainer("moeck_a", num_epoch=2)
    trained_a = a.train(ds)

    ck = str(tmp_path / "moe1f1b_ck")
    b = trainer("moeck_b", num_epoch=1, checkpoint_dir=ck)
    b.train(ds)
    c = trainer("moeck_c", num_epoch=2, checkpoint_dir=ck, resume=True)
    trained_c = c.train(ds)
    assert len(c.history) == len(a.history) - len(b.history)
    # an expert-weight leaf (the P("pp","ep")-sharded kind) and a dense
    # leaf both land exactly where the uninterrupted run does
    for path in (
        ("layer_0", "moe_mlp", "w_in"),
        ("layer_1", "attention", "query", "kernel"),
    ):
        want = trained_a.params
        got = trained_c.params
        for kpart in path:
            want, got = want[kpart], got[kpart]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
        )
