"""Checkpoint/resume tests (a capability the reference lacks — SURVEY §5)."""

import numpy as np

from distkeras_tpu.checkpoint import CheckpointManager
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.ops.losses import get_optimizer
from distkeras_tpu.training.step import TrainState, make_train_step


def _state():
    model = Model.from_flax(MLP(features=(8,), num_classes=2), input_shape=(4,))
    opt = get_optimizer("adam", 1e-2)
    return model, opt, TrainState.create(model, opt, rng=0)


def test_save_restore_roundtrip(tmp_path):
    model, opt, state = _state()
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(0, state=state, meta={"note": "t"})
    assert mgr.latest_step() == 0
    restored = mgr.restore(0, like={"state": state, "meta": {"note": "t"}})
    w0 = state.params["Dense_0"]["kernel"]
    np.testing.assert_array_equal(
        np.asarray(restored["state"].params["Dense_0"]["kernel"]), np.asarray(w0)
    )
    mgr.close()


def test_resume_continues_training(tmp_path):
    model, opt, state = _state()
    step_fn = make_train_step(model, opt, "categorical_crossentropy", donate=False)
    rng = np.random.default_rng(0)
    batch = {
        "features": rng.normal(size=(16, 4)).astype(np.float32),
        "label": (rng.normal(size=(16,)) > 0).astype(np.float32),
    }
    for _ in range(3):
        state, _ = step_fn(state, batch)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(3, state=state, ps_center=state.params, ps_num_updates=7)
    restored = mgr.restore(
        3, like={"state": state, "ps": {"center": state.params, "num_updates": 0}}
    )
    assert int(restored["state"].step) == 3
    assert int(restored["ps"]["num_updates"]) == 7
    # resumed state steps forward identically to the uninterrupted one
    cont, _ = step_fn(restored["state"], batch)
    direct, _ = step_fn(state, batch)
    np.testing.assert_allclose(
        np.asarray(cont.params["Dense_0"]["kernel"]),
        np.asarray(direct.params["Dense_0"]["kernel"]),
        atol=1e-7,
    )
    mgr.close()


def test_max_to_keep(tmp_path):
    model, opt, state = _state()
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    for s in (0, 1, 2, 3):
        mgr.save(s, state=state)
    assert mgr.latest_step() == 3
    assert len(mgr.all_steps()) <= 2
    mgr.close()


def test_restore_into_sharded_template(tmp_path):
    """A checkpoint restores directly into a GSPMD-sharded TrainState: the
    template's shardings are honored, so params come back distributed."""
    import jax
    from distkeras_tpu.models.bert import bert_tiny_mlm
    from distkeras_tpu.parallel.gspmd import sharded_train_state
    from distkeras_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 2, "tp": 4})
    model = bert_tiny_mlm(seq_len=8, vocab_size=64)
    opt = get_optimizer("adam", 1e-3)
    state, _ = sharded_train_state(model, opt, mesh, rng=0)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(0, state=state)
    restored = mgr.restore(0, like={"state": state})["state"]
    k = restored.params["layer_0"]["mlp_in"]["kernel"]
    # sharding preserved: mlp dim split over tp=4
    assert {s.data.shape for s in k.addressable_shards} == {(128, 128)}
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(k)),
        np.asarray(jax.device_get(state.params["layer_0"]["mlp_in"]["kernel"])),
    )
    mgr.close()
