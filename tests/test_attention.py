"""Attention kernel tests: blocked softmax correctness + ring attention
against the dense reference, on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import (
    dot_product_attention,
    ring_attention,
    ring_self_attention,
)
from distkeras_tpu.parallel.mesh import make_mesh


def _qkv(rng, B=2, S=32, H=2, D=8):
    return (
        np.asarray(rng.normal(size=(B, S, H, D)), np.float32),
        np.asarray(rng.normal(size=(B, S, H, D)), np.float32),
        np.asarray(rng.normal(size=(B, S, H, D)), np.float32),
    )


def test_attention_matches_naive_softmax(rng):
    q, k, v = _qkv(rng, B=1, S=8, H=1, D=4)
    out = np.asarray(dot_product_attention(q, k, v))
    # naive reference
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(4)
    w = np.exp(scores - scores.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_causal_mask(rng):
    q, k, v = _qkv(rng, B=1, S=6, H=1, D=4)
    out = np.asarray(dot_product_attention(q, k, v, causal=True))
    # position 0 attends only to itself -> equals v[0]
    np.testing.assert_allclose(out[0, 0, 0], v[0, 0, 0], atol=1e-5)


def test_ring_attention_matches_dense(rng):
    q, k, v = _qkv(rng, B=2, S=64, H=2, D=8)
    mesh = make_mesh({"dp": 2, "sp": 4})
    out = ring_self_attention(q, k, v, mesh, seq_axis="sp")
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_ring_attention_full_sp_axis(rng):
    q, k, v = _qkv(rng, B=1, S=64, H=2, D=8)
    mesh = make_mesh({"sp": 8})
    out = ring_self_attention(q, k, v, mesh, seq_axis="sp")
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_causal_ring_attention_matches_dense(rng):
    q, k, v = _qkv(rng, B=2, S=64, H=2, D=8)
    mesh = make_mesh({"dp": 2, "sp": 4})
    out = ring_self_attention(q, k, v, mesh, seq_axis="sp", causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_causal_ring_attention_full_sp(rng):
    q, k, v = _qkv(rng, B=1, S=64, H=1, D=8)
    mesh = make_mesh({"sp": 8})
    out = ring_self_attention(q, k, v, mesh, seq_axis="sp", causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


@pytest.mark.slow
def test_ring_attention_gradients_match_dense(rng):
    """Ring attention differentiates through ppermute hops."""
    q, k, v = _qkv(rng, B=2, S=32, H=1, D=8)
    mesh = make_mesh({"sp": 8})

    def loss_ring(q, k, v):
        return jnp.mean(ring_self_attention(q, k, v, mesh, seq_axis="sp") ** 2)

    def loss_dense(q, k, v):
        return jnp.mean(dot_product_attention(q, k, v) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3)
