"""Prefix-cache unit behavior (serving/prefix_cache.py), model-free.

The cache is structure-agnostic: any pytree whose KV leaves are
``[1, L, ...]`` and whose index leaves are 1-D works, so these tests use
a tiny hand-built template and exact integer-valued K/V — block
identity, splice placement, ref-counting, and LRU eviction are all
checkable to the element without a model in sight. Engine-integrated
behavior (parity, hit-after-evict round trips) lives in test_serving.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.serving.prefix_cache import PrefixCache

L, H, D, BT = 32, 2, 4, 4  # cache length, heads, head_dim, block tokens


def _template():
    return {
        "layer_0": {
            "cached_key": jnp.zeros((1, L, H, D), jnp.float32),
            "cached_value": jnp.zeros((1, L, H, D), jnp.float32),
            "cache_index": jnp.zeros((1,), jnp.int32),
        },
        "pos_index": jnp.zeros((1,), jnp.int32),
    }


def _filled_cache(base: float):
    """A 'prefilled' cache whose row t holds value base + t — block
    content is recognizable after any copy."""
    t = jnp.arange(L, dtype=jnp.float32).reshape(1, L, 1, 1)
    return {
        "layer_0": {
            "cached_key": jnp.broadcast_to(base + t, (1, L, H, D)),
            "cached_value": jnp.broadcast_to(base + 100 + t, (1, L, H, D)),
            "cache_index": jnp.full((1,), L, jnp.int32),
        },
        "pos_index": jnp.full((1,), L, jnp.int32),
    }


def _cache(blocks=4, **kw):
    tpl = _template()
    probe = PrefixCache(tpl, block_tokens=BT, budget_bytes=1 << 20)
    return PrefixCache(tpl, block_tokens=BT,
                       budget_bytes=blocks * probe.bytes_per_block, **kw)


def test_capacity_from_byte_budget():
    pc = _cache(blocks=3)
    # Two KV leaves of [BT, H, D] float32 per block.
    assert pc.bytes_per_block == 2 * BT * H * D * 4
    assert pc.capacity == 3
    with pytest.raises(ValueError, match="zero blocks"):
        PrefixCache(_template(), block_tokens=BT, budget_bytes=7)
    with pytest.raises(ValueError, match="exceeds cache length"):
        PrefixCache(_template(), block_tokens=L + 1)


def test_insert_match_and_whole_prompt_cap():
    pc = _cache()
    prompt = list(range(10))  # 2 complete blocks + ragged tail
    assert pc.insert(prompt, _filled_cache(0.0)) == 2
    m = pc.match(prompt)
    assert m.matched_tokens == 2 * BT and len(m.ids) == 2
    pc.release(m)
    # A prompt that IS exactly the cached blocks never fully matches:
    # prefill needs >= 1 uncached token to produce the first logits.
    m = pc.match(prompt[:8])
    assert m.matched_tokens == BT
    pc.release(m)
    # Diverging after one block matches only the shared block.
    m = pc.match(prompt[:4] + [99, 98, 97, 96, 95])
    assert m.matched_tokens == BT
    pc.release(m)
    assert pc.probe(prompt) == 2 * BT  # probe agrees, no pinning
    s = pc.stats()
    assert s["lookups"] == 3 and s["hit_requests"] == 3
    assert s["blocks_used"] == 2


def test_splice_places_blocks_and_leaves_indices_alone():
    pc = _cache()
    src = _filled_cache(1000.0)
    prompt = list(range(9))
    pc.insert(prompt, src)
    m = pc.match(prompt)
    out = pc.splice(_template(), m.ids)
    got_k = np.asarray(out["layer_0"]["cached_key"])
    want_k = np.asarray(src["layer_0"]["cached_key"])
    matched = m.matched_tokens
    np.testing.assert_array_equal(got_k[0, :matched], want_k[0, :matched])
    got_v = np.asarray(out["layer_0"]["cached_value"])
    want_v = np.asarray(src["layer_0"]["cached_value"])
    np.testing.assert_array_equal(got_v[0, :matched], want_v[0, :matched])
    # Index leaves are the prefill chunk's job, not the splice's.
    assert int(out["layer_0"]["cache_index"][0]) == 0
    assert int(out["pos_index"][0]) == 0
    pc.release(m)


def test_refcount_blocks_eviction_until_release():
    pc = _cache(blocks=2)
    a = [1] * 12  # 3 complete blocks, capacity 2 -> stores 2
    assert pc.insert(a, _filled_cache(0.0)) == 2
    m = pc.match(a)  # pins both blocks
    b = [2] * 12
    assert pc.insert(b, _filled_cache(50.0)) == 0  # everything pinned
    assert pc.stats()["evicted_blocks"] == 0
    pc.release(m)
    assert pc.insert(b, _filled_cache(50.0)) == 2  # LRU-evicts a's chain
    assert pc.stats()["evicted_blocks"] == 2
    assert pc.probe(a) == 0 and pc.probe(b) == 2 * BT
    assert pc.blocks_used == 2  # never exceeds the budget


def test_lru_prefers_least_recently_used_leaf():
    pc = _cache(blocks=2)
    a, b = [1] * 5, [2] * 5  # one block each
    pc.insert(a, _filled_cache(0.0))
    pc.insert(b, _filled_cache(10.0))
    pc.release(pc.match([1] * 5))  # touch a: b becomes the LRU leaf
    pc.insert([3] * 5, _filled_cache(20.0))
    assert pc.probe([1] * 4 + [0]) == BT  # a survived
    assert pc.probe([2] * 4 + [0]) == 0  # b evicted
    assert pc.probe([3] * 4 + [0]) == BT


def test_registry_metrics_published():
    from distkeras_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    pc = _cache(blocks=2, registry=reg)
    pc.insert([1] * 9, _filled_cache(0.0))
    pc.release(pc.match([1] * 9))
    snap = reg.snapshot()
    assert snap["prefix_cache_blocks_capacity"]["value"] == 2
    assert snap["prefix_cache_blocks_used"]["value"] == 2
    assert snap["prefix_cache_hit_tokens_total"]["value"] == 2 * BT
    assert snap["prefix_cache_inserted_blocks_total"]["value"] == 2
    assert snap["prefix_cache_lookups_total"]["value"] == 1


def test_store_and_splice_compile_counts_stay_bounded():
    """Store and splice each compile once per pow2 block-count bucket —
    the same discipline as the engine's prefill buckets — and an insert
    is ONE batched store call however many blocks it adds."""
    pc = _cache(blocks=8)
    for base, toks in ((0, [1] * 9), (1, [2] * 17), (2, [3] * 29)):
        pc.insert(toks, _filled_cache(float(base)))
        pc.release(pc.match(toks))
    store_probe = getattr(pc._store, "_cache_size", None)
    if store_probe is not None:
        assert store_probe() <= 3  # buckets 2, 4, 8 (one per insert size)
    splice_probe = getattr(pc._splice, "_cache_size", None)
    m = pc.match([3] * 29)  # 6 complete blocks -> bucket 8
    pc.splice(_template(), m.ids)
    pc.release(m)
    if splice_probe is not None:
        assert splice_probe() <= 3  # buckets 1, 2, 8 at most so far
