"""Failure-handling tests: retries, exactly-once commits, watchdog,
PS checkpoint/resume through the trainer."""

import threading
import time

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.parallel.ha import (
    ParameterServerUnavailable,
    RetryingClient,
    StampingClient,
    watchdog,
)
from distkeras_tpu.parallel.protocols import DOWNPOURProtocol
from distkeras_tpu.parallel.ps import ParameterServerService


class FlakyClient:
    """Fails the first N calls of each method, then succeeds."""

    def __init__(self, inner, fail_first=2):
        self.inner = inner
        self.fails = {"pull": fail_first, "commit": fail_first}

    def pull(self):
        if self.fails["pull"] > 0:
            self.fails["pull"] -= 1
            raise ConnectionError("flaky")
        return self.inner.pull()

    def commit(self, payload):
        if self.fails["commit"] > 0:
            self.fails["commit"] -= 1
            raise ConnectionError("flaky")
        return self.inner.commit(payload)


def _service():
    ps = ParameterServerService(
        DOWNPOURProtocol(), {"w": np.zeros(2, np.float32)}, 2
    )
    ps.start()
    return ps


def test_retrying_client_recovers():
    ps = _service()
    try:
        client = RetryingClient(FlakyClient(ps.client()), base_delay=0.01)
        center, n = client.pull()
        assert n == 0
        client.commit({"delta": {"w": np.ones(2, np.float32)}})
        center, n = client.pull()
        assert np.allclose(center["w"], 1.0)
    finally:
        ps.stop()


def test_retrying_client_gives_up():
    class AlwaysDown:
        def pull(self):
            raise ConnectionError("down")

    client = RetryingClient(AlwaysDown(), max_retries=2, base_delay=0.01)
    with pytest.raises(ParameterServerUnavailable):
        client.pull()


def test_duplicate_commits_applied_once():
    ps = _service()
    try:
        c = ps.client()
        payload = {"delta": {"w": np.ones(2, np.float32)}, "commit_id": "w0:1"}
        c.commit(payload)
        c.commit(payload)  # replay (e.g. retry after timeout)
        c.pull()  # barrier
        assert ps.num_commits == 1
        assert ps.num_duplicates == 1
        assert np.allclose(ps.get_model()["w"], 1.0)
    finally:
        ps.stop()


def test_stamping_client_ids_unique():
    seen = []

    class Capture:
        def commit(self, payload):
            seen.append(payload["commit_id"])

        def pull(self):
            return None, 0

    c = StampingClient(Capture(), worker_id=3)
    for _ in range(5):
        c.commit({"delta": {}})
    assert len(set(seen)) == 5
    assert all(s.startswith("w3:") for s in seen)


def test_health_snapshot():
    ps = _service()
    try:
        h = ps.health()
        assert h["running"] is True
        assert h["num_commits"] == 0
    finally:
        ps.stop()
    assert ps.health()["running"] is False


def test_watchdog_fires_on_stall():
    stalls = []
    ev = threading.Event()
    t = watchdog(
        lambda: {"running": True, "num_commits": 0},
        on_stall=lambda h: (stalls.append(h), ev.set()),
        interval=0.05,
        stall_after=2,
    )
    assert ev.wait(timeout=2.0)
    t.stop_event.set()
    assert stalls


@pytest.mark.slow
def test_trainer_ps_checkpoint_and_resume(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 6)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    model = Model.from_flax(MLP(features=(8,), num_classes=2), input_shape=(6,))

    t1 = dk.DOWNPOUR(
        model, worker_optimizer="adam", learning_rate=0.01, num_workers=2,
        batch_size=16, num_epoch=2, communication_window=2,
        checkpoint_dir=str(tmp_path / "ps_ckpt"),
    )
    trained1 = t1.train(ds)
    # a final checkpoint exists
    from distkeras_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ps_ckpt"))
    assert mgr.latest_step() is not None
    mgr.close()

    # resume: center starts from the checkpoint, not from fresh init
    t2 = dk.DOWNPOUR(
        model, worker_optimizer="adam", learning_rate=0.01, num_workers=2,
        batch_size=16, num_epoch=1, communication_window=2,
        checkpoint_dir=str(tmp_path / "ps_ckpt"), resume=True,
    )
    trained2 = t2.train(ds)
    preds = trained2.predict(x)
    acc = float(np.mean(np.argmax(preds, -1) == y))
    assert acc > 0.8, acc


@pytest.mark.slow
def test_compressed_deltas_train(tmp_path):
    """bf16 delta compression end-to-end, in-process and over gRPC."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    model = Model.from_flax(MLP(features=(16,), num_classes=2), input_shape=(8,))
    for transport in ("inprocess", "grpc"):
        trainer = dk.ADAG(
            model, worker_optimizer="adam", learning_rate=0.01,
            num_workers=2, batch_size=16, num_epoch=4, communication_window=4,
            transport=transport, compress_deltas=True,
        )
        trained = trainer.train(ds)
        preds = trained.predict(x)
        acc = float(np.mean(np.argmax(preds, -1) == y))
        assert acc > 0.85, (transport, acc)


@pytest.mark.slow
def test_kitchen_sink_async(tmp_path):
    """Feature interaction: ADAG with islands (2x2 devices), gRPC transport,
    bf16 delta compression, and PS checkpointing — all at once."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    model = Model.from_flax(MLP(features=(16,), num_classes=2), input_shape=(8,))
    trainer = dk.ADAG(
        model, worker_optimizer="adam", learning_rate=0.01,
        num_workers=2, devices_per_worker=2, batch_size=8, num_epoch=4,
        communication_window=3, transport="grpc", compress_deltas=True,
        checkpoint_dir=str(tmp_path / "ks"),
    )
    trained = trainer.train(ds)
    assert trainer.parameter_server.num_commits > 0
    preds = trained.predict(x)
    acc = float(np.mean(np.argmax(preds, -1) == y))
    assert acc > 0.85, acc
    # resume pass picks up the checkpointed center
    t2 = dk.ADAG(
        model, worker_optimizer="adam", learning_rate=0.01,
        num_workers=2, devices_per_worker=2, batch_size=8, num_epoch=1,
        communication_window=3, transport="grpc", compress_deltas=True,
        checkpoint_dir=str(tmp_path / "ks"), resume=True,
    )
    t2.train(ds)
    assert t2.parameter_server.num_commits > 0
