"""Multi-tenant QoS: weighted deficit round robin + token-rate quotas
(distkeras_tpu.serving.scheduler) and their engine/wire integration.

The contract under test:

- within one priority class, tenants share token bandwidth by DRR
  (weighted; single-tenant degenerates to exact FIFO — covered by the
  original scheduler tests in test_serving.py);
- priority classes still dominate tenants (no tenant fairness across
  classes);
- quotas reject TYPED at submit (``TenantOverQuota``), never kill an
  admitted stream, and unused charge is credited back at completion;
- preempt/park requeue still lands at the FRONT of its class across
  tenants (the paged-KV contract);
- end to end: an engine with bin1 framing, batched admission, tenant
  scheduling and quotas — armed RecompileAuditor stays silent and
  greedy output is token-identical to the JSONL path and generate().
"""

import asyncio
from collections import Counter

import numpy as np
import pytest

from distkeras_tpu.serving import (
    Request,
    Scheduler,
    TenantOverQuota,
)

pytestmark = []


# -- scheduler-level --------------------------------------------------------
def test_drr_fair_service_within_class():
    async def go():
        s = Scheduler(max_depth=64, drr_quantum=4)
        for _ in range(6):
            s.submit(Request([1], 4, tenant="a"), now=0.0)
        for _ in range(6):
            s.submit(Request([2], 4, tenant="b"), now=0.0)
        order = [s.pop(now=1.0).tenant for _ in range(12)]
        # Equal weights + equal cost: neither tenant ever leads by more
        # than one service turn, even though "a" enqueued all of its
        # backlog first — the flooding-tenant starvation shape.
        for i in range(2, 13, 2):
            c = Counter(order[:i])
            assert abs(c["a"] - c["b"]) <= 1, order

    asyncio.run(go())


def test_drr_weights_bias_token_bandwidth():
    async def go():
        s = Scheduler(max_depth=64, drr_quantum=4,
                      tenant_weights={"a": 2.0})
        for _ in range(12):
            s.submit(Request([1], 4, tenant="a"), now=0.0)
        for _ in range(12):
            s.submit(Request([2], 4, tenant="b"), now=0.0)
        first = Counter(s.pop(now=1.0).tenant for _ in range(12))
        # Weight 2 vs 1 under full backlog: ~2/3 of service.
        assert first["a"] >= 7, first

    asyncio.run(go())


def test_priority_classes_dominate_tenant_fairness():
    async def go():
        s = Scheduler(max_depth=16)
        a = Request([1], 4, tenant="a", priority=1)
        b = Request([2], 4, tenant="b", priority=0)
        c = Request([3], 4, tenant="c", priority=1)
        for r in (a, b, c):
            s.submit(r, now=0.0)
        # The better class is served FIRST regardless of tenant DRR.
        assert s.pop(now=0.0) is b
        assert s.pop(now=0.0) is a and s.pop(now=0.0) is c

    asyncio.run(go())


def test_quota_typed_reject_refund_and_isolation():
    async def go():
        s = Scheduler(max_depth=64, tenant_quotas={"a": 10.0},
                      quota_burst_s=1.0)  # capacity 10 tokens
        r1 = Request([1], 8, tenant="a")
        s.submit(r1, now=100.0)
        with pytest.raises(TenantOverQuota):
            s.submit(Request([1], 8, tenant="a"), now=100.0)
        # Unmetered tenants are untouched by a's quota.
        s.submit(Request([1], 8, tenant="b"), now=100.0)
        # r1 finished after 2 tokens: 6 of its 8 charged come back.
        r1.out_tokens = [1, 2]
        s.release_quota(r1)
        s.submit(Request([1], 6, tenant="a"), now=100.0)
        # ...and the refund is idempotent (terminal paths may race).
        s.release_quota(r1)
        stats = s.tenant_stats()
        assert stats["a"]["over_quota_rejects"] == 1
        assert stats["a"]["quota"]["rate_tokens_per_s"] == 10.0

    asyncio.run(go())


def test_quota_refills_over_time():
    async def go():
        s = Scheduler(max_depth=8, tenant_quotas={"a": 10.0},
                      quota_burst_s=1.0)
        s.submit(Request([1], 10, tenant="a"), now=0.0)
        with pytest.raises(TenantOverQuota):
            s.submit(Request([1], 10, tenant="a"), now=0.1)
        # One second later the bucket refilled its full capacity.
        s.submit(Request([1], 10, tenant="a"), now=1.2)

    asyncio.run(go())


def test_requeue_front_crosses_tenants():
    async def go():
        s = Scheduler(max_depth=8)
        x = Request([1], 4, tenant="a")
        y = Request([2], 4, tenant="b")
        s.submit(x, now=0.0)
        s.submit(y, now=0.0)
        assert s.pop(now=0.0) is x
        # Preemption returns x to the FRONT of the whole class — peek
        # and pop must both see it before b's queued request (the paged
        # engine's admission-park gate reads peek()).
        s.requeue(x)
        assert s.peek() is x
        assert s.pop(now=0.0) is x and s.pop(now=0.0) is y

    asyncio.run(go())


def test_submit_many_is_per_request_typed():
    async def go():
        s = Scheduler(max_depth=2, tenant_quotas={"q": 1.0},
                      quota_burst_s=1.0)
        reqs = [Request([1], 1, tenant="q"),   # takes the whole budget
                Request([1], 9, tenant="q"),   # over quota
                Request([1], 1),               # fits
                Request([1], 1)]               # queue full (depth 2)
        out = s.submit_many(reqs, now=0.0)
        assert out[0] is None and out[2] is None
        assert isinstance(out[1], TenantOverQuota)
        assert type(out[3]).__name__ == "QueueFullError"
        assert len(s) == 2

    asyncio.run(go())


def test_serving_config_flags_forward_wire_and_quotas():
    """The deploy canary must validate the production wire config: the
    shared replica-flag builder forwards --wire and the tenant knobs."""
    import argparse

    from distkeras_tpu.run import _parse_tenant_rates, _serving_config_flags

    args = argparse.Namespace(
        prefix_cache_mb=0.0, prefix_block=16, top_k=None,
        prefill_chunk=None, paged=False, kv_pool_mb=0.0,
        kv_block_tokens=16, max_context=None, draft_model=None,
        wire="bin1", tenant_quota=["acme=100", "free=10"],
        tenant_weight=["acme=2"])
    flags = _serving_config_flags(args)
    assert flags[flags.index("--wire") + 1] == "bin1"
    assert flags.count("--tenant-quota") == 2
    assert "acme=100" in flags and "free=10" in flags
    assert flags[flags.index("--tenant-weight") + 1] == "acme=2"
    assert _parse_tenant_rates(["a=1.5", "b=2"], "--x") == {
        "a": 1.5, "b": 2.0}
    with pytest.raises(SystemExit):
        _parse_tenant_rates(["nope"], "--x")
    with pytest.raises(SystemExit):
        _parse_tenant_rates(["a=fast"], "--x")


# -- engine + wire, end to end ----------------------------------------------
VOCAB = 64


@pytest.fixture(scope="module")
def lm():
    from distkeras_tpu.models.bert import gpt_tiny

    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(0)


def test_bin1_tenants_quotas_auditor_token_identical(lm):
    """THE acceptance invariant: with bin1 framing, batched admission,
    tenant DRR and quotas all enabled, the armed RecompileAuditor stays
    silent and greedy output is token-identical to the JSONL path and
    to one-shot generate()."""
    from distkeras_tpu.inference.generate import generate
    from distkeras_tpu.serving import (
        ServingClient, ServingEngine, ServingServer,
    )
    from distkeras_tpu.telemetry import RecompileAuditor

    model, variables = lm
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, VOCAB, size=(n,)).tolist()
               for n in (5, 7, 3, 6)]
    auditor = RecompileAuditor()
    engine = ServingEngine(
        model, variables, slots=2, max_queue=16,
        auditor=auditor, arm_auditor_after_warmup=True,
        tenant_quotas={"hot": 16.0}, quota_burst_s=1.0,
        tenant_weights={"vip": 2.0})

    async def go():
        server = ServingServer(engine, port=0)
        await server.start()
        port = server.port
        # JSONL first (sequential), then bin1 (pipelined + batched):
        # same prompts, same tenants, must stream identical tokens.
        outs_jsonl = []
        async with ServingClient("127.0.0.1", port) as c:
            for p, t in zip(prompts, ("a", "b", "vip", "hot")):
                done = await c.generate(p, 4, tenant=t)
                assert done["tenant"] == t
                outs_jsonl.append(done["tokens"])
        async with ServingClient("127.0.0.1", port,
                                 wire_mode="bin1") as c:
            assert c.proto == "bin1"
            dones = await asyncio.gather(*(
                c.generate(p, 4, tenant=t)
                for p, t in zip(prompts, ("a", "b", "vip", "hot"))))
            outs_bin = [d["tokens"] for d in dones]
            batch = await c.generate_batch(prompts, 4, tenant="batch")
            outs_batch = [d["tokens"] for d in batch]
            # Quota enforcement over the wire: "hot" holds 16 tokens of
            # burst; a request that can NEVER fit is typed-rejected at
            # submit while the stream-level API stays usable
            # (25 tokens fits the context cap, never the 16-token
            # burst).
            with pytest.raises(TenantOverQuota):
                await c.generate(prompts[1][:3], 25, tenant="hot")
            health = await c.healthz()
        await server.stop()
        return outs_jsonl, outs_bin, outs_batch, health

    outs_jsonl, outs_bin, outs_batch, health = asyncio.run(go())
    assert outs_jsonl == outs_bin == outs_batch
    for p, got in zip(prompts, outs_jsonl):
        want = generate(model, variables, np.asarray([p], np.int32), 4,
                        greedy=True)[0].tolist()
        assert got == want
    # The auditor was armed after warmup and never raised: compile-once
    # held through bin1 + batched admission + tenant scheduling.
    assert engine.decode_compile_count() in (1, -1)
    tenants = health["tenants"]
    assert tenants["hot"]["over_quota_rejects"] == 1
    assert tenants["vip"]["completed"] >= 2  # jsonl + bin1 rounds
    assert "quota" in tenants["hot"]


def test_engine_flood_is_shed_typed_and_isolated(lm):
    """A flooding tenant is shed at submit with TYPED rejects while an
    honest tenant's simultaneously-submitted work completes untouched —
    the scheduler-level adversarial contract, engine-integrated."""
    from distkeras_tpu.serving import ServingEngine

    model, variables = lm
    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, VOCAB, size=(4,)).tolist()
               for _ in range(12)]
    engine = ServingEngine(
        model, variables, slots=2, max_queue=32,
        tenant_quotas={"flood": 8.0}, quota_burst_s=1.0)

    async def go():
        task = asyncio.create_task(engine.run())
        honest, sheds = [], 0
        for i, p in enumerate(prompts):
            honest.append(engine.submit(p, 2, tenant="honest"))
            try:
                engine.submit(p, 4, tenant="flood")
            except TenantOverQuota:
                sheds += 1
        outs = [await r.result() for r in honest]
        engine.shutdown(drain=True)
        await task
        return outs, sheds

    outs, sheds = asyncio.run(go())
    assert len(outs) == len(prompts) and all(len(o) == 2 for o in outs)
    # 8 tok/s, 1 s burst: two 4-token requests fit, the rest shed typed.
    assert sheds == len(prompts) - 2, sheds
    snap = engine.tenant_snapshot()
    assert snap["flood"]["over_quota_rejects"] == sheds
    assert snap["honest"]["completed"] == len(prompts)
