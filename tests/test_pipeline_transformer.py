"""Pipeline-parallel training of a small transformer LM: embedding and head
outside the pipelined trunk, 4 residual attention+MLP blocks as stages over
pp=4. Demonstrates pp is a *training* axis, not a demo."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.slow  # integration-scale; run with `pytest -m ''`

from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.parallel.mesh import make_mesh
from distkeras_tpu.parallel.pipeline import pipeline_apply, stack_stage_params

D, H, VOCAB, SEQ = 32, 2, 64, 16


def block_fn(params, x):
    """One pre-LN transformer block; shape-preserving [B, S, D]."""
    def ln(z):
        mu = z.mean(-1, keepdims=True)
        var = ((z - mu) ** 2).mean(-1, keepdims=True)
        return (z - mu) * jax.lax.rsqrt(var + 1e-6)

    B, S, _ = x.shape
    y = ln(x)
    qkv = y @ params["wqkv"]  # [B,S,3D]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (B, S, H, D // H)
    attn = dot_product_attention(
        q.reshape(shape), k.reshape(shape), v.reshape(shape), causal=True
    ).reshape(B, S, D)
    x = x + attn @ params["wo"]
    y = ln(x)
    return x + jnp.tanh(y @ params["w1"]) @ params["w2"]


def init_stage(rng):
    s = 0.08
    return {
        "wqkv": rng.normal(size=(D, 3 * D)).astype(np.float32) * s,
        "wo": rng.normal(size=(D, D)).astype(np.float32) * s,
        "w1": rng.normal(size=(D, 2 * D)).astype(np.float32) * s,
        "w2": rng.normal(size=(2 * D, D)).astype(np.float32) * s,
    }


def test_pipelined_transformer_trains(rng):
    P, M, B = 4, 4, 2
    mesh = make_mesh({"pp": P})
    embed = rng.normal(size=(VOCAB, D)).astype(np.float32) * 0.1
    stages = stack_stage_params([init_stage(rng) for _ in range(P)])
    params = {"embed": jnp.asarray(embed), "stages": jax.tree.map(jnp.asarray, stages)}

    tokens = rng.integers(0, VOCAB, size=(M, B, SEQ)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=-1)

    def loss_fn(params):
        x = params["embed"][tokens]  # [M, B, S, D]
        # pipeline over the stage trunk; microbatch axis M
        out = pipeline_apply(block_fn, params["stages"], x, mesh)
        logits = out @ params["embed"].T  # tied head [M, B, S, V]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()

    opt = optax.adam(3e-3)
    opt_state = opt.init(params)
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    losses = []
    p = params
    for _ in range(12):
        loss, g = grad_fn(p)
        updates, opt_state = opt.update(g, opt_state, p)
        p = optax.apply_updates(p, updates)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.1, losses
    assert np.isfinite(losses).all()
