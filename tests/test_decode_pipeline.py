"""Overlapped decode pipeline: depth-1 dispatch-before-harvest must be
token-identical to the serialized depth-0 engine in EVERY mode, with the
armed RecompileAuditor silent, and its bookkeeping (the one speculative
in-flight tick after a slot finishes) must leave pool accounting exact.

The parity pairs here are engine-vs-engine AND engine-vs-generate():
pipelining only defers the host's READ of each tick — the same ticks run
in the same order over the same state — so any divergence is a pipeline
bug, not model noise.
"""

import asyncio
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from distkeras_tpu.inference.generate import generate  # noqa: E402
from distkeras_tpu.models.bert import gpt_tiny  # noqa: E402
from distkeras_tpu.serving import ServingEngine  # noqa: E402
from distkeras_tpu.telemetry import RecompileAuditor  # noqa: E402


@pytest.fixture(scope="module")
def tiny_lm():
    model = gpt_tiny(seq_len=64, vocab_size=61)
    return model, model.init(0)


def _prompts(n, seed=0, lo=3, hi=11, vocab=61):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=int(k)).tolist()
            for k in rng.integers(lo, hi, size=n)]


def _run_engine(engine, prompts, new_tokens):
    async def main():
        task = asyncio.create_task(engine.run(idle_poll_s=0.01))
        reqs = [engine.submit(p, new_tokens) for p in prompts]
        outs = [await r.result() for r in reqs]
        engine.shutdown(drain=True)
        await task
        return outs

    return asyncio.run(main())


def _engine(tiny_lm, depth, **kw):
    model, variables = tiny_lm
    return ServingEngine(model, variables, slots=2, pipeline_depth=depth,
                         auditor=RecompileAuditor(),
                         arm_auditor_after_warmup=True, **kw)


MODES = {
    "dense": {},
    "paged": {"kv_pool_blocks": 24, "kv_block_tokens": 8},
    "chunked_prefix": {"prefill_chunk": 4, "prefix_cache_mb": 0.5},
}


@pytest.mark.parametrize("mode", sorted(MODES))
def test_pipeline_parity_token_identical(tiny_lm, mode):
    """Depth 1 == depth 0, greedy, per mode, at slots=2 — with the
    auditor armed after warmup (a pipelined retrace would raise). The
    engine-vs-engine pair is THE pipeline invariant: the same ticks in
    the same order, only the harvest deferred. (generate() parity at
    slots>1 carries the documented pre-existing batch-width tie
    envelope, so the absolute anchor runs at slots=1 below.)"""
    prompts = _prompts(8, seed=1)
    new_tokens = 10
    got = {}
    for depth in (0, 1):
        engine = _engine(tiny_lm, depth, **MODES[mode])
        got[depth] = _run_engine(engine, prompts, new_tokens)
        assert engine.decode_compile_count() in (1, -1)
        assert engine.auditor.compiles("serving_decode") == 1
    assert got[0] == got[1], f"{mode}: depth-1 output diverged from depth-0"


@pytest.mark.parametrize("mode", sorted(MODES))
def test_pipelined_engine_matches_generate_slots1(tiny_lm, mode):
    """Absolute anchor: the pipelined engine at slots=1 (where the
    engine's bitwise-parity promise is unconditional) reproduces
    generate() token for token, per mode."""
    model, variables = tiny_lm
    prompts = _prompts(5, seed=2)
    new_tokens = 8
    kw = dict(MODES[mode])
    engine = ServingEngine(model, variables, slots=1, pipeline_depth=1,
                           auditor=RecompileAuditor(),
                           arm_auditor_after_warmup=True, **kw)
    got = _run_engine(engine, prompts, new_tokens)
    assert engine.auditor.compiles("serving_decode") == 1
    for p, toks in zip(prompts, got):
        ref = generate(model, variables, np.asarray([p], np.int32),
                       new_tokens, greedy=True)[0].tolist()
        assert toks == ref, f"{mode}: pipelined stream diverged from generate"


def test_pipeline_parity_paged_preempt_resume(tiny_lm):
    """An oversubscribed pool (preempt + requeue + resume) stays
    token-identical under the pipelined loop: growth/preemption are
    barriers, so the round trip always sees fully-harvested state."""
    model, variables = tiny_lm
    prompts = _prompts(6, seed=7, lo=8, hi=16)
    new_tokens = 12
    got = {}
    for depth in (0, 1):
        engine = _engine(tiny_lm, depth, kv_pool_blocks=7,
                         kv_block_tokens=4)
        got[depth] = _run_engine(engine, prompts, new_tokens)
        assert engine.auditor.compiles("serving_decode") == 1
    assert got[0] == got[1]
    for p, toks in zip(prompts, got[1]):
        ref = generate(model, variables, np.asarray([p], np.int32),
                       new_tokens, greedy=True)[0].tolist()
        assert toks == ref


def test_pipeline_parity_speculative(tiny_lm):
    """Speculative mode under the pipelined loop (a spec tick harvests
    before the next dispatch; fallback ticks interleave) — draft==target
    sanity config, slots=1 for the bitwise promise, auditor armed over
    draft/verify/fallback."""
    model, variables = tiny_lm
    prompts = _prompts(5, seed=11)
    new_tokens = 9
    got = {}
    for depth in (0, 1):
        engine = ServingEngine(
            model, variables, slots=1, pipeline_depth=depth,
            draft_model=model, draft_variables=variables, spec_k=3,
            auditor=RecompileAuditor(), arm_auditor_after_warmup=True)
        got[depth] = _run_engine(engine, prompts, new_tokens)
        for name in ("serving_decode", "serving_draft", "serving_verify"):
            assert engine.auditor.compiles(name) == 1, name
    assert got[0] == got[1]
    for p, toks in zip(prompts, got[1]):
        ref = generate(model, variables, np.asarray([p], np.int32),
                       new_tokens, greedy=True)[0].tolist()
        assert toks == ref


def test_pipeline_parity_sharded_tp2(tiny_lm):
    """GSPMD tp=2 engine, pipelined: explicit shardings + deferred
    harvest keep one executable per callable and token identity."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (tier-1 runs with virtual CPUs)")
    from distkeras_tpu.parallel.mesh import serving_mesh

    model = gpt_tiny(seq_len=64, vocab_size=64)
    variables = model.init(0)
    prompts = _prompts(4, seed=5, vocab=64)
    new_tokens = 8
    got = {}
    for depth in (0, 1):
        engine = ServingEngine(
            model, variables, slots=2, pipeline_depth=depth,
            mesh=serving_mesh({"tp": 2}, devices=jax.devices()[:2]),
            auditor=RecompileAuditor(), arm_auditor_after_warmup=True)
        got[depth] = _run_engine(engine, prompts, new_tokens)
        assert engine.auditor.compiles("serving_decode") == 1
    assert got[0] == got[1]
    for p, toks in zip(prompts, got[1]):
        ref = generate(model, variables, np.asarray([p], np.int32),
                       new_tokens, greedy=True)[0].tolist()
        assert toks == ref


def test_one_extra_tick_after_finish_accounting_exact(tiny_lm):
    """When a slot finishes at tick N, tick N+1 is already in flight and
    ran one speculative row for it. The teardown must (a) drop that
    row's output, (b) roll back the optimistic watermark advance before
    adopting blocks — so the trie never claims the in-flight write and
    the pool's block accounting balances exactly — and (c) leave the
    adopted chain re-matchable: a follow-up identical prompt is a
    full-prefix hit with a token-identical continuation."""
    model, variables = tiny_lm
    engine = _engine(tiny_lm, 1, kv_pool_blocks=24, kv_block_tokens=4)
    pool = engine.kv_pool
    prompt = _prompts(1, seed=13, lo=9, hi=10)[0]
    new_tokens = 7

    out1 = _run_engine(engine, [prompt], new_tokens)[0]
    # All slot-owned state was released: tables fully sentinel, lens 0.
    assert all(int(b) == engine._sentinel
               for b in np.asarray(engine._tables).ravel())
    assert np.all(np.asarray(engine._lens) == 0)
    # The adopted chain covers exactly the COMPLETE blocks of the
    # harvested sequence: prompt + streamed tokens minus the last
    # sampled token (never fed) — the speculative in-flight write's
    # position must NOT be claimed.
    used = pool.capacity - pool.blocks_free
    fed = len(prompt) + new_tokens - 1
    assert used == fed // engine.kv_block_tokens

    # Re-admitting the same prompt must hit the adopted prefix and
    # continue token-identically.
    engine.reopen()
    hits_before = pool.stats()["hit_requests"]
    out2 = _run_engine(engine, [prompt], new_tokens)[0]
    assert out2 == out1
    assert pool.stats()["hit_requests"] > hits_before
    ref = generate(model, variables, np.asarray([prompt], np.int32),
                   new_tokens, greedy=True)[0].tolist()
    assert out1 == ref


def test_full_context_finish_at_block_boundary(tiny_lm):
    """A request whose prompt + max_new fills max_context EXACTLY, with
    the block size dividing the limit: at depth 1 the finishing tick's
    optimistic watermark advance puts ``_lens`` at the limit one full
    loop iteration before the harvest frees the slot, so the growth
    probe observes a live slot whose next-write block index is one past
    the table's last column. That row needs no growth (it is finishing);
    probing it must not index out of bounds and kill the engine."""
    model, variables = tiny_lm
    limit = 32
    got = {}
    for depth in (0, 1):
        engine = ServingEngine(
            model, variables, slots=2, pipeline_depth=depth,
            kv_pool_blocks=12, kv_block_tokens=8, max_context=limit,
            auditor=RecompileAuditor(), arm_auditor_after_warmup=True)
        prompt = _prompts(1, seed=23, lo=12, hi=13)[0]  # 12 tokens
        got[depth] = _run_engine(engine, [prompt], limit - len(prompt))[0]
        assert len(got[depth]) == limit - len(prompt)
        assert engine.auditor.compiles("serving_decode") == 1
    assert got[0] == got[1]


def test_parked_idle_engine_does_not_hot_spin(tiny_lm):
    """A fully-parked paged queue (pool dry, head parked, zero active
    slots) must WAIT on the arrival event, not re-enter the loop every
    iteration doing only the park check — and must still admit the
    parked request the moment blocks free (pool version moves + kick).

    The dry pool is constructed the way a disaggregated decode replica
    sees it: block rows held outside the engine (here: a direct
    ``pool.alloc``), so admission can neither allocate nor find a
    preemption victim and the head parks with nothing running."""
    model, variables = tiny_lm

    async def main():
        engine = ServingEngine(model, variables, slots=2,
                               pipeline_depth=1, kv_pool_blocks=8,
                               kv_block_tokens=4)
        pool = engine.kv_pool
        held = pool.alloc(8)  # the whole pool, from outside the engine
        assert held is not None
        # Count loop iterations via the expire() call at the top of
        # every iteration (metrics.sample is skipped on the idle path).
        iters = 0
        orig_expire = engine.scheduler.expire

        def counting_expire(now):
            nonlocal iters
            iters += 1
            return orig_expire(now)

        engine.scheduler.expire = counting_expire
        task = asyncio.create_task(engine.run(idle_poll_s=0.05))
        req = engine.submit([1, 2, 3, 4, 5], 4)  # needs 2 blocks: parks
        await asyncio.sleep(0.3)
        assert not req.done.is_set(), "request ran on a dry pool?"
        it0 = iters
        await asyncio.sleep(0.25)
        spun = iters - it0
        pool.free(held)          # blocks return; pool version moves
        engine.scheduler.kick()  # the wake the import path also sends
        toks = await asyncio.wait_for(req.result(), 10)
        engine.shutdown(drain=True)
        await task
        return spun, toks

    spun, toks = asyncio.run(main())
    assert toks, "parked request never completed after blocks freed"
    # 0.25 s at a 0.05 s idle poll ≈ 5 wakeups; a hot spin is thousands.
    assert spun <= 30, f"parked engine spun {spun} iterations in 0.25s"


def test_pipeline_depth_validated(tiny_lm):
    model, variables = tiny_lm
    with pytest.raises(ValueError, match="pipeline_depth"):
        ServingEngine(model, variables, pipeline_depth=2)


def test_tick_timeline_and_debugz_surface(tiny_lm):
    """The dispatch→harvest tick lane and the debugz pipeline block are
    populated by a real run, JSON-safe, and rendered by the pretty
    pages."""
    engine = _engine(tiny_lm, 1)
    _run_engine(engine, _prompts(3, seed=17), 6)
    lane = engine.tick_timeline()
    assert lane, "no ticks logged"
    for tk in lane:
        assert tk["kind"] in ("decode", "spec")
        assert tk["t_harvest"] >= tk["t_dispatch"]
        assert tk["host_gap_s"] >= 0.0
    dz = engine.debugz()
    assert dz["pipeline"]["depth"] == 1
    assert dz["pipeline"]["inflight"] is None  # drained at shutdown
    json.dumps(dz)  # JSON-safe
    s = engine.metrics.summary()
    assert "host_gap_p50_s" in s and "device_idle_ratio" in s

    from distkeras_tpu.serving.debugz import format_debugz, format_tracez

    page = format_debugz(dz)
    assert "pipeline: depth=1" in page
    lane_txt = format_tracez({"recent": [], "records": 0,
                              "ticks": lane[-5:]})
    assert "tick lane" in lane_txt
