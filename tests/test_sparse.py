"""SparseColumn (CSR) + DenseTransformer: real sparse->dense semantics
(reference DenseTransformer converted Spark SparseVector columns)."""

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.data.sparse import SparseColumn
from distkeras_tpu.data.transformers import DenseTransformer


def _random_sparse(n=40, dim=16, density=0.2, seed=0):
    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(n, dim)).astype(np.float32)
    dense[rng.random(size=dense.shape) > density] = 0.0
    return dense, SparseColumn.from_dense(dense)


def test_dense_roundtrip():
    dense, sp = _random_sparse()
    assert sp.shape == dense.shape
    assert sp.nnz == int((dense != 0).sum())
    np.testing.assert_array_equal(sp.to_dense(), dense)
    np.testing.assert_array_equal(np.asarray(sp), dense)  # __array__


def test_from_rows_reference_sparsevector_form():
    rows = [([0, 3], [1.0, 2.0]), ([], []), ([5], [7.0])]
    sp = SparseColumn.from_rows(rows, dim=6)
    want = np.zeros((3, 6), np.float32)
    want[0, 0], want[0, 3], want[2, 5] = 1.0, 2.0, 7.0
    np.testing.assert_array_equal(sp.to_dense(), want)


def test_row_selection_stays_sparse():
    dense, sp = _random_sparse()
    idx = np.array([5, 2, 2, 31])
    sel = sp[idx]
    assert isinstance(sel, SparseColumn)
    np.testing.assert_array_equal(sel.to_dense(), dense[idx])
    sl = sp[3:11]
    np.testing.assert_array_equal(sl.to_dense(), dense[3:11])


def test_dataset_ops_keep_sparse_and_match_dense():
    dense, sp = _random_sparse()
    label = (dense.sum(axis=1) > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=sp, label=label)
    dd = dk.Dataset.from_arrays(features=dense, label=label)

    shuf_s, shuf_d = ds.shuffle(seed=3), dd.shuffle(seed=3)
    assert isinstance(shuf_s["features"], SparseColumn)
    np.testing.assert_array_equal(
        np.asarray(shuf_s["features"]), shuf_d["features"]
    )
    parts = ds.shuffle(seed=1).partitions(3)
    assert sum(p.num_rows for p in parts) == ds.num_rows
    assert all(isinstance(p["features"], SparseColumn) for p in parts)
    cat = parts[0].concat(parts[1]).concat(parts[2])
    assert isinstance(cat["features"], SparseColumn)
    rep = ds.repeat(2)
    assert rep.num_rows == 2 * ds.num_rows
    assert isinstance(rep["features"], SparseColumn)


def test_dense_transformer_densifies():
    dense, sp = _random_sparse()
    ds = dk.Dataset.from_arrays(features=sp)
    out = DenseTransformer("features", "features_dense").transform(ds)
    got = out["features_dense"]
    assert isinstance(got, np.ndarray) and got.dtype == np.float32
    assert got.flags["C_CONTIGUOUS"]
    np.testing.assert_array_equal(got, dense)


def test_training_on_sparse_features_end_to_end():
    """Sparse features -> DenseTransformer -> SingleTrainer: the reference
    workflow (SparseVector column densified before training)."""
    from distkeras_tpu.models.core import Model
    from distkeras_tpu.models.mlp import MLP

    rng = np.random.default_rng(0)
    dense = rng.normal(size=(128, 16)).astype(np.float32)
    dense[rng.random(size=dense.shape) > 0.3] = 0.0
    w = rng.normal(size=(16,))
    label = (dense @ w > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(
        features=SparseColumn.from_dense(dense), label=label
    )
    ds = DenseTransformer("features", "features").transform(ds)
    model = Model.from_flax(MLP(features=(16,), num_classes=2), input_shape=(16,))
    t = dk.SingleTrainer(model, worker_optimizer="adam", learning_rate=0.02,
                         batch_size=32, num_epoch=8, seed=0)
    t.train(ds, shuffle=True)
    assert t.get_history()[-1]["accuracy"] > 0.9


def test_sparse_validation_errors():
    with pytest.raises(ValueError, match="indptr"):
        SparseColumn(np.array([1, 2]), np.array([0]), np.array([1.0]), 4)
    with pytest.raises(ValueError, match="out of range"):
        SparseColumn(np.array([0, 1]), np.array([9]), np.array([1.0]), 4)
    with pytest.raises(ValueError, match="mismatch"):
        SparseColumn(np.array([0, 2]), np.array([0, 1]), np.array([1.0]), 4)


def test_sparse_dataset_full_surface():
    """The column must work across the WHOLE Dataset surface (review
    findings): rows(), describe(), with_column, npz round-trip in CSR
    form, mixed concat, negative gather indices."""
    import os
    import tempfile

    dense, sp = _random_sparse(n=12, dim=6, seed=4)
    label = np.arange(12, dtype=np.float32)
    ds = dk.Dataset.from_arrays(features=sp, label=label)

    # rows(): scalar row indexing returns the dense row vector
    got = [r["features"] for r in ds.rows()]
    np.testing.assert_array_equal(np.stack(got), dense)

    # describe(): CSR-direct stats (zeros included), no densify
    st = ds.describe()["features"]
    assert st["mean"] == pytest.approx(float(dense.mean()), abs=1e-6)
    assert st["std"] == pytest.approx(float(dense.std()), abs=1e-6)
    assert st["min"] == pytest.approx(float(dense.min()), abs=1e-6)
    assert st["max"] == pytest.approx(float(dense.max()), abs=1e-6)

    # with_column preserves sparsity
    ds2 = ds.with_column("features2", sp)
    assert isinstance(ds2["features2"], SparseColumn)

    # npz round-trip stays CSR
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "ds.npz")
        ds.to_npz(p)
        back = dk.Dataset.from_npz(p)
        assert isinstance(back["features"], SparseColumn)
        np.testing.assert_array_equal(
            np.asarray(back["features"]), dense
        )
        np.testing.assert_array_equal(back["label"], label)

    # mixed sparse/dense concat: sparse wins, both operand orders
    dd = dk.Dataset.from_arrays(features=dense, label=label)
    for a, b in ((ds, dd), (dd, ds)):
        cat = a.concat(b)
        assert isinstance(cat["features"], SparseColumn)
        np.testing.assert_array_equal(
            np.asarray(cat["features"]), np.concatenate([dense, dense])
        )

    # negative indices behave like numpy at the column level (the
    # Dataset-level native gather rejects them for every column type)
    np.testing.assert_array_equal(
        np.asarray(sp[np.array([-1, 0])]), dense[[-1, 0]]
    )
    with pytest.raises(IndexError):
        sp[np.array([99])]


def test_sparse_scalar_negative_index_and_npz_collision_guard():
    import os
    import tempfile

    dense, sp = _random_sparse(n=6, dim=4, seed=8)
    np.testing.assert_array_equal(sp[-1], dense[-1])
    np.testing.assert_array_equal(sp[-2], dense[-2])
    with pytest.raises(IndexError):
        sp[6]
    with pytest.raises(IndexError):
        sp[-7]
    # reserved-suffix collision is rejected at save time, not lost silently
    ds = dk.Dataset.from_arrays(x__csr_mask=dense)
    with tempfile.TemporaryDirectory() as td:
        with pytest.raises(ValueError, match="__csr_"):
            ds.to_npz(os.path.join(td, "bad.npz"))


def test_sparse_npz_roundtrip_with_csr_in_name():
    """A SparseColumn whose own name contains '__csr_' must round-trip
    (base derivation strips the FINAL component suffix)."""
    import os
    import tempfile

    dense, sp = _random_sparse(n=5, dim=3, seed=2)
    ds = dk.Dataset.from_arrays(**{"a__csr_b": sp})
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "weird.npz")
        ds.to_npz(p)
        back = dk.Dataset.from_npz(p)
        assert isinstance(back["a__csr_b"], SparseColumn)
        np.testing.assert_array_equal(np.asarray(back["a__csr_b"]), dense)


def test_boolean_mask_selection_ndarray_parity():
    """ADVICE r4: a bool mask must select rows like ndarray fancy indexing
    (previously it survived to the indptr arithmetic as bool and raised a
    confusing IndexError — or silently mis-selected)."""
    dense, sp = _random_sparse(n=7, dim=5, seed=3)
    mask = np.array([True, False, True, True, False, False, True])
    np.testing.assert_array_equal(np.asarray(sp[mask]), dense[mask])
    # empty mask -> empty column, dim preserved
    none = sp[np.zeros(7, bool)]
    assert len(none) == 0 and none.dim == 5
    # wrong-length mask: loud IndexError, same as ndarray
    with pytest.raises(IndexError, match="boolean mask"):
        sp[np.array([True, False])]
