"""Parameter-server service tests, including a concurrency hammer
(SURVEY §5: make races impossible by construction, then prove it)."""

import threading

import numpy as np

from distkeras_tpu.parallel.protocols import DOWNPOURProtocol, DynSGDProtocol
from distkeras_tpu.parallel.ps import ParameterServerService


def test_pull_commit_roundtrip():
    ps = ParameterServerService(DOWNPOURProtocol(), {"w": np.zeros(3, np.float32)}, 2)
    ps.start()
    try:
        client = ps.client()
        center, n = client.pull()
        assert np.allclose(center["w"], 0.0) and n == 0
        client.commit({"delta": {"w": np.ones(3, np.float32)}})
        # pull is ordered after the commit in the single queue
        center, n = client.pull()
        assert np.allclose(center["w"], 1.0)
        assert n == 1
    finally:
        ps.stop()


def test_get_model_after_stop():
    ps = ParameterServerService(DOWNPOURProtocol(), {"w": np.zeros(2)}, 1)
    ps.start()
    ps.client().commit({"delta": {"w": np.full(2, 5.0)}})
    ps.client().pull()  # barrier
    ps.stop()
    assert np.allclose(ps.get_model()["w"], 5.0)


def test_concurrent_commit_hammer():
    """All commits must land exactly once: center == sum of all deltas."""
    ps = ParameterServerService(DOWNPOURProtocol(), {"w": np.zeros(1, np.float64)}, 8)
    ps.start()
    per_thread, n_threads = 200, 8

    def hammer(tid):
        c = ps.client()
        for i in range(per_thread):
            c.commit({"delta": {"w": np.ones(1, np.float64)}})
            if i % 50 == 0:
                c.pull()

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ps.client().pull()  # drain barrier
    ps.stop()
    assert ps.num_commits == per_thread * n_threads
    assert np.allclose(ps.get_model()["w"], per_thread * n_threads)


def test_dynsgd_counter_consistency_under_concurrency():
    """num_updates must equal total commits; staleness never negative."""
    ps = ParameterServerService(DynSGDProtocol(), {"w": np.zeros(1)}, 4)
    ps.start()

    def worker(tid):
        c = ps.client()
        _, last = c.pull()
        for _ in range(100):
            c.commit({"delta": {"w": np.ones(1)}, "last_update": last})
            _, last = c.pull()

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ps.stop()
    assert ps.num_updates == 400
    # each delta damped by 1/(staleness+1) <= 1 -> center <= 400, > 0
    w = ps.get_model()["w"][0]
    assert 0 < w <= 400
