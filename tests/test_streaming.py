"""Streaming source abstraction + streaming inference (VERDICT r1 missing
item 3: the reference's Kafka example needs a broker/socket source
abstraction, not just an in-process simulation)."""

import socket
import threading

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.data.streaming import (
    GeneratorSource,
    QueueSource,
    SocketSource,
    StreamingPredictor,
    producer_thread,
    send_stream_batch,
)
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP


@pytest.fixture(scope="module")
def trained():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(512, 16)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    t = dk.SingleTrainer(
        Model.from_flax(MLP(features=(32,), num_classes=2), input_shape=(16,)),
        worker_optimizer="adam", learning_rate=1e-2, batch_size=64, num_epoch=5,
    )
    return t.train(ds)


def test_queue_source_stream(trained):
    rng = np.random.default_rng(1)
    src = QueueSource(timeout=10.0)
    batches = [rng.normal(size=(40, 16)).astype(np.float32) for _ in range(5)]
    producer_thread(src, batches)
    outs = []
    stats = StreamingPredictor(trained, max_batch=64).run(
        src, lambda x, p: outs.append((x, p))
    )
    assert stats["batches"] == 5 and stats["rows"] == 200
    assert all(p.shape == (40, 2) for _, p in outs)
    # padded-tail predictions match direct predict
    direct = trained.predict(batches[0])
    np.testing.assert_allclose(outs[0][1], direct, atol=1e-5)


def test_socket_source_round_trip(trained):
    rng = np.random.default_rng(2)
    src = SocketSource(port=0)
    batches = [
        {"features": rng.normal(size=(24, 16)).astype(np.float32)}
        for _ in range(4)
    ]

    def produce():
        s = socket.create_connection((src.host, src.port))
        for b in batches:
            send_stream_batch(s, b)
        send_stream_batch(s, None)  # end-of-stream
        s.close()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    outs = []
    stats = StreamingPredictor(trained, max_batch=32).run(
        src, lambda x, p: outs.append(p)
    )
    t.join(timeout=10)
    assert stats["batches"] == 4 and stats["rows"] == 96
    np.testing.assert_allclose(
        outs[0], trained.predict(batches[0]["features"]), atol=1e-5
    )


def test_generator_source_and_oversize_batches(trained):
    rng = np.random.default_rng(3)
    big = rng.normal(size=(150, 16)).astype(np.float32)  # > max_batch
    outs = []
    stats = StreamingPredictor(trained, max_batch=64).run(
        GeneratorSource([big]), lambda x, p: outs.append(p)
    )
    assert stats["rows"] == 150
    assert outs[0].shape == (150, 2)
    np.testing.assert_allclose(outs[0], trained.predict(big), atol=1e-5)


def test_kafka_source_gated():
    with pytest.raises(ImportError, match="kafka-python"):
        from distkeras_tpu.data.streaming import KafkaSource

        KafkaSource("topic")
