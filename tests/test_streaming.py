"""Streaming source abstraction + streaming inference (VERDICT r1 missing
item 3: the reference's Kafka example needs a broker/socket source
abstraction, not just an in-process simulation)."""

import socket
import threading

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.data.streaming import (
    GeneratorSource,
    QueueSource,
    SocketSource,
    StreamingPredictor,
    producer_thread,
    send_stream_batch,
)
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP


@pytest.fixture(scope="module")
def trained():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(512, 16)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    t = dk.SingleTrainer(
        Model.from_flax(MLP(features=(32,), num_classes=2), input_shape=(16,)),
        worker_optimizer="adam", learning_rate=1e-2, batch_size=64, num_epoch=5,
    )
    return t.train(ds)


def test_queue_source_stream(trained):
    rng = np.random.default_rng(1)
    src = QueueSource(timeout=10.0)
    batches = [rng.normal(size=(40, 16)).astype(np.float32) for _ in range(5)]
    producer_thread(src, batches)
    outs = []
    stats = StreamingPredictor(trained, max_batch=64).run(
        src, lambda x, p: outs.append((x, p))
    )
    assert stats["batches"] == 5 and stats["rows"] == 200
    assert all(p.shape == (40, 2) for _, p in outs)
    # padded-tail predictions match direct predict
    direct = trained.predict(batches[0])
    np.testing.assert_allclose(outs[0][1], direct, atol=1e-5)


def test_socket_source_round_trip(trained):
    rng = np.random.default_rng(2)
    src = SocketSource(port=0)
    batches = [
        {"features": rng.normal(size=(24, 16)).astype(np.float32)}
        for _ in range(4)
    ]

    def produce():
        s = socket.create_connection((src.host, src.port))
        for b in batches:
            send_stream_batch(s, b)
        send_stream_batch(s, None)  # end-of-stream
        s.close()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    outs = []
    stats = StreamingPredictor(trained, max_batch=32).run(
        src, lambda x, p: outs.append(p)
    )
    t.join(timeout=10)
    assert stats["batches"] == 4 and stats["rows"] == 96
    np.testing.assert_allclose(
        outs[0], trained.predict(batches[0]["features"]), atol=1e-5
    )


def test_generator_source_and_oversize_batches(trained):
    rng = np.random.default_rng(3)
    big = rng.normal(size=(150, 16)).astype(np.float32)  # > max_batch
    outs = []
    stats = StreamingPredictor(trained, max_batch=64).run(
        GeneratorSource([big]), lambda x, p: outs.append(p)
    )
    assert stats["rows"] == 150
    assert outs[0].shape == (150, 2)
    np.testing.assert_allclose(outs[0], trained.predict(big), atol=1e-5)


def test_kafka_source_gated():
    with pytest.raises(ImportError, match="kafka-python"):
        from distkeras_tpu.data.streaming import KafkaSource

        KafkaSource("topic")


class _FakeKafkaMessage:
    def __init__(self, value: bytes):
        self.value = value


class _FakeKafkaConsumer:
    """In-process stand-in for kafka.KafkaConsumer (VERDICT r3 task 6):
    replays a canned list of messages for the subscribed topic, records
    constructor kwargs and close(), so KafkaSource.__iter__'s framing /
    value_fn / lifecycle logic actually executes under test."""

    messages_by_topic: dict = {}
    instances: list = []

    def __init__(self, topic, bootstrap_servers=None, **kwargs):
        self.topic = topic
        self.bootstrap_servers = bootstrap_servers
        self.kwargs = kwargs
        self.closed = False
        self._msgs = list(self.messages_by_topic.get(topic, []))
        _FakeKafkaConsumer.instances.append(self)

    def __iter__(self):
        for m in self._msgs:
            yield _FakeKafkaMessage(m)

    def close(self):
        self.closed = True


@pytest.fixture
def fake_kafka(monkeypatch):
    import sys
    import types

    mod = types.ModuleType("kafka")
    mod.KafkaConsumer = _FakeKafkaConsumer
    _FakeKafkaConsumer.messages_by_topic = {}
    _FakeKafkaConsumer.instances = []
    monkeypatch.setitem(sys.modules, "kafka", mod)
    return _FakeKafkaConsumer


def test_kafka_source_iterates_with_default_npz_value_fn(fake_kafka):
    """Default value_fn is the pickle-free npz PyTree codec: wire frames
    produced by serialize_pytree round-trip through the consumer."""
    from distkeras_tpu.data.streaming import KafkaSource
    from distkeras_tpu.utils.pytree import serialize_pytree

    batches = [
        {"x": np.arange(6, dtype=np.float32).reshape(2, 3)},
        {"x": np.ones((1, 3), np.float32)},
    ]
    fake_kafka.messages_by_topic["feats"] = [
        serialize_pytree(b) for b in batches
    ]
    src = KafkaSource("feats", bootstrap_servers="broker:9092",
                     group_id="g1")
    got = list(src)
    assert len(got) == 2
    np.testing.assert_array_equal(got[0]["x"], batches[0]["x"])
    np.testing.assert_array_equal(got[1]["x"], batches[1]["x"])
    # constructor kwargs reached the consumer; close() propagates
    consumer = fake_kafka.instances[-1]
    assert consumer.bootstrap_servers == "broker:9092"
    assert consumer.kwargs["group_id"] == "g1"
    src.close()
    assert consumer.closed


def test_kafka_source_custom_value_fn_feeds_predictor(fake_kafka, trained):
    """End-to-end: Kafka micro-batches (custom decoder) through the padded
    StreamingPredictor — the reference's Kafka streaming-inference example
    (examples/ Kafka notebook), minus the broker."""
    from distkeras_tpu.data.streaming import KafkaSource

    rng = np.random.default_rng(7)
    raw = [rng.normal(size=(5, 16)).astype(np.float32) for _ in range(3)]
    fake_kafka.messages_by_topic["rows"] = [a.tobytes() for a in raw]
    src = KafkaSource(
        "rows",
        value_fn=lambda b: np.frombuffer(b, np.float32).reshape(-1, 16),
    )
    outs = []
    stats = StreamingPredictor(trained, max_batch=8).run(
        src, lambda x, p: outs.append(p)
    )
    assert stats["rows"] == 15 and stats["batches"] == 3
    np.testing.assert_allclose(
        np.concatenate(outs),
        trained.predict(np.concatenate(raw)),
        atol=1e-5,
    )
