"""True multi-process jax.distributed test: two Python processes (4 virtual
CPU devices each) form one 8-device global mesh via
distkeras_tpu.parallel.distributed and run a psum + a GSPMD train step —
the single-machine simulation of the multi-host DCN bootstrap."""

import socket
import subprocess
import sys
import textwrap
import pytest

pytestmark = pytest.mark.slow  # integration-scale; run with `pytest -m ''`

WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    port, pid = sys.argv[1], int(sys.argv[2])
    from distkeras_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    # collective sanity: psum of (process_index + 1) over all devices
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = distributed.global_mesh({"dp": 8})

    from jax import shard_map

    @jax.jit
    def allsum(x):
        return shard_map(
            lambda v: jax.lax.psum(v, "dp"),
            mesh=mesh, in_specs=P("dp"), out_specs=P(),
        )(x)

    import numpy as np
    local = np.full(8, float(jax.process_index() + 1), np.float32)
    # global array: each process contributes its addressable shards
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local[:4].reshape(4)
    )
    total = allsum(arr)
    # devices 0-3 hold proc0's value... psum sums device values:
    # 4 devices * 1.0 + 4 devices * 2.0 = 12
    val = float(np.asarray(total)[0] if np.ndim(total) else total)
    assert abs(val - 12.0) < 1e-5, val
    print(f"MULTIHOST_OK p{pid} psum={val}")
    """
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_mesh_and_psum():
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, str(port), str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, err[-2000:]
        assert "MULTIHOST_OK" in out
