"""Keras 3 model façade: dist-keras notebooks hand trainers a Keras model
(reference ``distkeras/trainers.py`` § ``Trainer.__init__(keras_model, ...)``);
Model.from_keras adapts one onto the PyTree engine via the JAX backend."""

import numpy as np
import pytest

keras = pytest.importorskip("keras")

import distkeras_tpu as dk  # noqa: E402
from distkeras_tpu.models.core import Model  # noqa: E402


@pytest.fixture
def keras_mlp():
    if keras.backend.backend() != "jax":
        pytest.skip("keras JAX backend not active")
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(12,)),
            keras.layers.Dense(16, activation="relu"),
            keras.layers.Dense(2),
        ]
    )
    return model


def test_from_keras_init_and_apply(keras_mlp):
    m = Model.from_keras(keras_mlp)
    variables = m.init(0)
    x = np.random.default_rng(0).normal(size=(4, 12)).astype(np.float32)
    out, state = m.apply(variables, x, train=False)
    assert out.shape == (4, 2)
    assert state == {}


def test_keras_model_trains_with_single_trainer(keras_mlp):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 12)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    # the reference pattern: pass the Keras model straight to the trainer
    trainer = dk.SingleTrainer(
        keras_mlp, worker_optimizer="adam", learning_rate=0.01,
        loss="categorical_crossentropy", batch_size=32, num_epoch=6,
    )
    trained = trainer.train(ds)
    preds = trained.predict(x)
    acc = float(np.mean(np.argmax(preds, -1) == y))
    assert acc > 0.85, acc


def test_keras_model_trains_with_async_trainer(keras_mlp):
    rng = np.random.default_rng(1)
    x = rng.normal(size=(256, 12)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    trainer = dk.DOWNPOUR(
        keras_mlp, worker_optimizer="adam", learning_rate=0.01,
        loss="categorical_crossentropy", num_workers=2, batch_size=16,
        num_epoch=4, communication_window=4,
    )
    trained = trainer.train(ds)
    assert trainer.parameter_server.num_commits > 0
    preds = trained.predict(x)
    acc = float(np.mean(np.argmax(preds, -1) == y))
    assert acc > 0.8, acc
