import numpy as np

from distkeras_tpu.utils.pytree import (
    deserialize_pytree,
    pytree_add,
    pytree_mean,
    pytree_scale,
    pytree_sub,
    serialize_pytree,
)


def _tree():
    return {
        "dense": {"kernel": np.ones((3, 2), np.float32), "bias": np.zeros(2, np.float32)},
        "out": {"kernel": np.full((2, 1), 2.0, np.float32)},
    }


def test_arithmetic():
    t = _tree()
    two = pytree_add(t, t)
    assert np.allclose(two["dense"]["kernel"], 2.0)
    zero = pytree_sub(t, t)
    assert np.allclose(zero["out"]["kernel"], 0.0)
    half = pytree_scale(t, 0.5)
    assert np.allclose(half["dense"]["kernel"], 0.5)


def test_mean():
    a, b = _tree(), pytree_scale(_tree(), 3.0)
    m = pytree_mean([a, b])
    assert np.allclose(m["dense"]["kernel"], 2.0)


def test_serialize_roundtrip_with_like():
    t = _tree()
    data = serialize_pytree(t)
    assert isinstance(data, bytes)
    back = deserialize_pytree(data, like=t)
    ft, fb = _flatten(t), _flatten(back)
    assert set(ft) == set(fb)
    for k in ft:
        assert np.array_equal(ft[k], fb[k]), k


def test_serialize_roundtrip_structural():
    t = _tree()
    back = deserialize_pytree(serialize_pytree(t))
    assert np.array_equal(back["dense"]["kernel"], t["dense"]["kernel"])
    assert np.array_equal(back["out"]["kernel"], t["out"]["kernel"])


def test_serialize_list_structure():
    t = {"layers": [np.arange(3), np.arange(4)]}
    back = deserialize_pytree(serialize_pytree(t))
    assert np.array_equal(back["layers"][0], np.arange(3))
    assert np.array_equal(back["layers"][1], np.arange(4))


def _flatten(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out.update(_flatten(v, prefix + k + "/"))
        else:
            out[prefix + k] = v
    return out


def test_serialize_keras_model_parity_helpers():
    from distkeras_tpu.utils import deserialize_keras_model, serialize_keras_model
    from distkeras_tpu.models.core import Model, TrainedModel
    from distkeras_tpu.models.mlp import MLP

    model = Model.from_flax(MLP(features=(4,), num_classes=2), input_shape=(3,))
    trained = TrainedModel(model, model.init(7))
    blob = serialize_keras_model(trained)
    back = deserialize_keras_model(blob, model)
    x = np.zeros((2, 3), np.float32)
    np.testing.assert_allclose(trained.predict(x), back.predict(x), atol=1e-7)


def test_serialize_bfloat16_roundtrip():
    import ml_dtypes

    t = {"w": np.full((3, 2), 1.5, ml_dtypes.bfloat16),
         "b": np.zeros(2, np.float32)}
    back = deserialize_pytree(serialize_pytree(t))
    assert back["w"].dtype == ml_dtypes.bfloat16
    assert np.allclose(back["w"].astype(np.float32), 1.5)
    assert back["b"].dtype == np.float32


def test_pytree_ops_stay_numpy_for_host_inputs():
    """PS-side math must not bounce host arrays through the accelerator."""
    a = {"w": np.ones(4, np.float32)}
    b = {"w": np.full(4, 2.0, np.float32)}
    out = pytree_add(a, b)
    assert isinstance(out["w"], np.ndarray)  # not a jax.Array
    out = pytree_sub(a, b)
    assert isinstance(out["w"], np.ndarray)
    # device inputs stay device
    import jax.numpy as jnp

    da = {"w": jnp.ones(4)}
    db = {"w": jnp.ones(4)}
    dout = pytree_add(da, db)
    import jax

    assert isinstance(dout["w"], jax.Array)
