"""Notebook-form examples (VERDICT r1 missing item 4): valid nbformat-4
JSON whose code cells compile. (Execution is covered by the scripts the
notebooks mirror — examples/mnist.py, examples/real_data_digits.py —
and was verified manually; compiling keeps the suite fast.)"""

import json
import pathlib

import pytest

NOTEBOOKS = sorted(
    (pathlib.Path(__file__).parent.parent / "examples" / "notebooks").glob("*.ipynb")
)


def test_notebooks_exist():
    names = {p.name for p in NOTEBOOKS}
    assert {"mnist.ipynb", "workflow.ipynb"} <= names


@pytest.mark.parametrize("path", NOTEBOOKS, ids=lambda p: p.name)
def test_notebook_wellformed_and_compiles(path):
    nb = json.loads(path.read_text())
    assert nb["nbformat"] == 4
    code_cells = [c for c in nb["cells"] if c["cell_type"] == "code"]
    assert code_cells
    for i, cell in enumerate(code_cells):
        compile("".join(cell["source"]), f"{path.name}:cell{i}", "exec")
