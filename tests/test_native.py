"""Native data-plane tests. The library is built by `make -C native`; when
absent, the numpy fallbacks must produce identical results, so every test
runs both paths when possible."""

import numpy as np
import pytest

from distkeras_tpu.data import native


def test_available_after_build():
    # The repo builds the library in CI/setup; if this fails, run
    # `make -C native`.
    assert native.available()


def test_parse_csv():
    data = b"1.5,2,3\n4,5.25,6\n7,8,9.125\n"
    out = native.parse_csv(data, rows=3, cols=3)
    np.testing.assert_allclose(
        out, [[1.5, 2, 3], [4, 5.25, 6], [7, 8, 9.125]]
    )


def test_parse_csv_malformed():
    with pytest.raises(ValueError):
        native.parse_csv(b"1,xx,3\n", rows=1, cols=3)


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.normal(size=(100, 17)).astype(np.float32)
    idx = rng.integers(0, 100, size=64)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_pack_batch_plain_and_fused():
    src = np.arange(40, dtype=np.float32).reshape(10, 4)
    out = native.pack_batch(src, start=2, batch=3)
    np.testing.assert_array_equal(out, src[2:5])
    fused = native.pack_batch(src, start=0, batch=2, scale=2.0, shift=1.0)
    np.testing.assert_allclose(fused, src[:2] * 2.0 + 1.0)


def test_permutation_is_deterministic_permutation():
    p1 = native.permutation(1000, seed=42)
    p2 = native.permutation(1000, seed=42)
    p3 = native.permutation(1000, seed=43)
    np.testing.assert_array_equal(p1, p2)
    assert not np.array_equal(p1, p3)
    np.testing.assert_array_equal(np.sort(p1), np.arange(1000))


def test_column_minmax():
    x = np.array([[3.0, -1.5], [10.0, 0.0]], np.float32)
    lo, hi = native.column_minmax(x)
    assert lo == -1.5 and hi == 10.0


def test_parse_csv_rejects_extra_fields():
    # extra field must not silently misalign following rows
    with pytest.raises(ValueError):
        native.parse_csv(b"1,2,3,4\n5,6,7\n", rows=2, cols=3)
