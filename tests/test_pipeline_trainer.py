"""PipelineTrainer: pp as a trainer capability (VERDICT r1 weakness 7).

Covers: (a) gradient equivalence of the pipelined forward vs the plain
sequential model, (b) end-to-end pp(+dp) training reaching parity accuracy
with the dp path on the same model/data, (c) params round-trip back to the
standard layout so the returned TrainedModel predicts.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # integration-scale; run with `pytest -m ''`

import distkeras_tpu as dk
from distkeras_tpu.models.bert import BertConfig, _make

VOCAB, SEQ = 64, 16


def _tiny_model():
    # dropout 0: the pipelined trunk is deterministic (no per-stage rng
    # streams), so exact-parity checks need the plain path deterministic too.
    cfg = BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_layers=2, num_heads=2,
        mlp_dim=64, max_seq_len=SEQ, dropout_rate=0.0,
    )
    return _make(cfg, SEQ, "bert_pico")


def _copy_task(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, VOCAB, size=(n, SEQ)).astype(np.int32)
    return dk.Dataset.from_arrays(features=x, label=x)


@pytest.mark.parametrize("remat", [False, True], ids=["plain", "remat"])
def test_pipeline_forward_matches_sequential(remat):
    model = _tiny_model()
    trainer = dk.PipelineTrainer(model, num_stages=2, num_microbatches=2,
                                 batch_size=8, remat=remat)
    from distkeras_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    variables = model.init(0)
    train_params, per_stage = trainer._split_params(variables["params"], 2)
    forward = trainer._make_forward(mesh, per_stage)

    rng = np.random.default_rng(1)
    batch = {
        "features": rng.integers(0, VOCAB, size=(8, SEQ)).astype(np.int32),
        "label": rng.integers(0, VOCAB, size=(8, SEQ)).astype(np.int32),
    }
    loss_pp, _ = forward(train_params, batch)

    def plain_loss(params):
        from distkeras_tpu.ops.losses import get_loss

        logits, _ = model.apply({"params": params}, batch["features"], train=False)
        return get_loss("categorical_crossentropy")(logits, batch["label"])

    loss_plain = plain_loss(variables["params"])
    np.testing.assert_allclose(
        float(loss_pp), float(loss_plain), rtol=2e-2, atol=2e-2
    )

    # Gradient equivalence on the first stage's attention query kernel and
    # the (non-pipelined) embedding.
    g_pp = jax.grad(lambda tp: forward(tp, batch)[0])(train_params)
    g_plain = jax.grad(plain_loss)(variables["params"])
    np.testing.assert_allclose(
        np.asarray(g_pp["rest"]["token_embed"]["embedding"], np.float32),
        np.asarray(g_plain["token_embed"]["embedding"], np.float32),
        rtol=5e-2, atol=5e-3,
    )
    q_pp = np.asarray(
        g_pp["stages"]["sub_0"]["attention"]["query"]["kernel"], np.float32
    )
    np.testing.assert_allclose(
        q_pp[0],
        np.asarray(g_plain["layer_0"]["attention"]["query"]["kernel"], np.float32),
        rtol=5e-2, atol=5e-3,
    )
    np.testing.assert_allclose(
        q_pp[1],
        np.asarray(g_plain["layer_1"]["attention"]["query"]["kernel"], np.float32),
        rtol=5e-2, atol=5e-3,
    )


def test_pipeline_training_parity_with_dp():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device mesh")
    ds = _copy_task(256)
    kwargs = dict(worker_optimizer="adam", learning_rate=3e-3, num_epoch=12, seed=0)
    pp = dk.PipelineTrainer(
        _tiny_model(), num_stages=2, num_microbatches=4, batch_size=64, **kwargs
    )  # auto mesh: dp=4 x pp=2 over 8 devices
    trained_pp = pp.train(ds)
    # Same GLOBAL batch of 64 (sync batch_size is per-worker: 8 x 8 devices).
    # NOTE: per-device batch 4 on this model hits a flaky XLA:CPU
    # ThunkExecutor abort on the virtual mesh (pre-existing, dp-only, not
    # TPU-relevant) — keep the per-device batch at 8.
    dp = dk.SynchronousDistributedTrainer(_tiny_model(), batch_size=8, **kwargs)
    trained_dp = dp.train(ds)

    acc_pp = pp.get_averaged_history()["accuracy"]
    acc_dp = dp.get_averaged_history()["accuracy"]
    # Both learn the copy task; pp matches dp within noise.
    assert pp.history[-1]["loss"] < pp.history[0]["loss"] * 0.5
    assert abs(acc_pp - acc_dp) < 0.15, (acc_pp, acc_dp)

    # Round-tripped params predict in the standard layout.
    x = np.asarray(ds["features"][:4])
    preds = trained_pp.predict(x)
    assert preds.shape == (4, SEQ, VOCAB)
    assert np.isfinite(preds).all()
    assert np.isfinite(trained_dp.predict(x)).all()


def test_pipeline_rejects_bad_shapes():
    model = _tiny_model()
    with pytest.raises(ValueError, match="not divisible into"):
        t = dk.PipelineTrainer(model, num_stages=2, num_microbatches=3,
                               batch_size=32)
        t._split_params(model.init(0)["params"], 3)  # 2 layers / 3 stages
    with pytest.raises(ValueError, match="needs a transformer-family"):
        from distkeras_tpu.models.mlp import mnist_mlp

        dk.PipelineTrainer(mnist_mlp())

def test_pipeline_trainer_interleaved_virtual_stages():
    """virtual_stages=2: a 4-layer model over pp=2 with 2 chunks/device
    trains, loss decreases, and params round-trip to the standard layout."""
    cfg = BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_layers=4, num_heads=2,
        mlp_dim=64, max_seq_len=SEQ, dropout_rate=0.0,
    )
    model = _make(cfg, SEQ, "bert_pico4")
    ds = _copy_task(128)
    trainer = dk.PipelineTrainer(
        model, worker_optimizer="adam", learning_rate=3e-3,
        num_stages=2, num_microbatches=4, virtual_stages=2,
        batch_size=32, num_epoch=6, seed=0,
    )
    trained = trainer.train(ds)
    assert trainer.history[-1]["loss"] < trainer.history[0]["loss"]

    # Forward parity: merged params drive the plain model identically to a
    # fresh-init forward of the same weights (layout round-trip is exact).
    x = np.asarray(ds["features"][:4])
    preds = trained.predict(x)
    assert preds.shape == (4, SEQ, VOCAB)
    assert np.isfinite(preds).all()

    # Split->merge is the identity on params.
    variables = model.init(0)
    tp, per_stage = trainer._split_params(variables["params"], 2)
    merged = trainer._merge_params(jax.device_get(tp), 2, per_stage)
    for i in range(4):
        np.testing.assert_array_equal(
            np.asarray(merged[f"layer_{i}"]["attention"]["query"]["kernel"]),
            np.asarray(variables["params"][f"layer_{i}"]["attention"]["query"]["kernel"]),
        )

def test_pipeline_trainer_with_dropout():
    """dropout_rate > 0 trains through the pipe: per-(tick, device) rng
    streams make the trunk stochastic in training, deterministic at eval."""
    cfg = BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_layers=2, num_heads=2,
        mlp_dim=64, max_seq_len=SEQ, dropout_rate=0.1,
    )
    model = _make(cfg, SEQ, "bert_pico_drop")
    ds = _copy_task(128)
    trainer = dk.PipelineTrainer(
        model, worker_optimizer="adam", learning_rate=3e-3,
        num_stages=2, num_microbatches=2, batch_size=32, num_epoch=4, seed=0,
    )
    trained = trainer.train(ds)
    assert trainer.history[-1]["loss"] < trainer.history[0]["loss"]

    # Same stage params + same key -> same loss; different key -> different
    # (dropout masks actually vary with the rng stream).
    import jax as _jax
    from distkeras_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"pp": 2}, devices=_jax.devices()[:2])
    variables = model.init(0)
    tp, per_stage = trainer._split_params(variables["params"], 2)
    forward = trainer._make_forward(mesh, per_stage)
    batch = {
        "features": np.asarray(ds["features"][:8], np.int32),
        "label": np.asarray(ds["label"][:8], np.int32),
    }
    k1, k2 = _jax.random.PRNGKey(1), _jax.random.PRNGKey(2)
    l1a, _ = forward(tp, batch, k1)
    l1b, _ = forward(tp, batch, k1)
    l2, _ = forward(tp, batch, k2)
    assert float(l1a) == float(l1b)
    assert float(l1a) != float(l2)

    # Eval path (train=False) is deterministic and finite.
    preds = trained.predict(batch["features"][:2])
    assert np.isfinite(preds).all()

def test_pipeline_trainer_moe_aux_loss():
    """MoE trunk through the pipe: aux load-balance loss is collected
    (masked to real ticks), reported in history, and training decreases
    the task loss."""
    cfg = BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_layers=2, num_heads=2,
        mlp_dim=64, max_seq_len=SEQ, dropout_rate=0.0,
        moe_experts=4, moe_top_k=2,
    )
    model = _make(cfg, SEQ, "bert_pico_moe")
    ds = _copy_task(128)
    trainer = dk.PipelineTrainer(
        model, worker_optimizer="adam", learning_rate=3e-3,
        num_stages=2, num_microbatches=2, batch_size=32, num_epoch=4,
        seed=0, aux_loss_weight=0.05,
    )
    trainer.train(ds)
    hist = trainer.get_history()
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert all("aux_loss" in h for h in hist)
    # Switch/GShard balance loss is >= 1 at any routing; finite always.
    assert all(np.isfinite(h["aux_loss"]) for h in hist)
    assert hist[0]["aux_loss"] > 0.5

    # The pipelined aux equals the plain model's summed sown aux on the
    # same params/batch (M=2 microbatches vs one full-batch apply).
    import jax as _jax
    from distkeras_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"pp": 2}, devices=_jax.devices()[:2])
    variables = model.init(0)
    tp, per_stage = trainer._split_params(variables["params"], 2)
    forward = trainer._make_forward(mesh, per_stage)
    batch = {
        "features": np.asarray(ds["features"][:8], np.int32),
        "label": np.asarray(ds["label"][:8], np.int32),
    }
    _, metrics = forward(tp, batch)
    _, state = model.apply(variables, batch["features"], train=True)
    plain_aux = float(sum(
        np.sum(np.asarray(l)) for l in _jax.tree.leaves(state["aux_loss"])
    ))
    # Not exactly equal: pipelined routing runs per microbatch (capacity
    # and load fractions computed over B/M tokens, not B) — same scale.
    assert abs(float(metrics["aux_loss"]) - plain_aux) / plain_aux < 0.25

def test_pipeline_trainer_moe_with_dropout():
    """The combined path — dropout rngs AND mutable aux collections through
    stage_fn(params, x, key) -> (y, aux) — trains and reports aux_loss."""
    cfg = BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_layers=2, num_heads=2,
        mlp_dim=64, max_seq_len=SEQ, dropout_rate=0.1,
        moe_experts=4, moe_top_k=2,
    )
    model = _make(cfg, SEQ, "bert_pico_moe_drop")
    ds = _copy_task(96)
    trainer = dk.PipelineTrainer(
        model, worker_optimizer="adam", learning_rate=3e-3,
        num_stages=2, num_microbatches=2, batch_size=32, num_epoch=3,
        seed=0, aux_loss_weight=0.05,
    )
    trained = trainer.train(ds)
    hist = trainer.get_history()
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert all(np.isfinite(h["aux_loss"]) for h in hist)
    preds = trained.predict(np.asarray(ds["features"][:2]))
    assert np.isfinite(preds).all()


def _moe_model(name="bert_pico_moe_ep", experts=4):
    cfg = BertConfig(
        vocab_size=VOCAB, hidden_size=32, num_layers=2, num_heads=2,
        mlp_dim=64, max_seq_len=SEQ, moe_experts=experts,
    )
    return _make(cfg, SEQ, name)


def test_pipeline_ep_stage_specs_shard_expert_dim():
    """Expert-weight leaves of the stacked stage params shard (pp, ep);
    everything else (router included) shards pp only — the dryrun-style
    spec assertion for the pipelined-MoE mesh (VERDICT r3 task 3)."""
    from jax.sharding import PartitionSpec as P

    model = _moe_model()
    trainer = dk.PipelineTrainer(model, num_stages=2, ep=2,
                                 num_microbatches=2, batch_size=8)
    params = model.init(0)["params"]
    train_params, _ = trainer._split_params(params, 2)
    specs = trainer._stage_specs(train_params["stages"], ep_size=2)
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]
    }
    expert = {k: s for k, s in flat.items() if k.endswith(("w_in", "w_out"))}
    assert expert and all(s == P("pp", "ep") for s in expert.values()), flat
    router = {k: s for k, s in flat.items() if k.endswith("router")}
    assert router and all(s == P("pp") for s in router.values())
    others = {k: s for k, s in flat.items() if k not in expert}
    assert all(s == P("pp") for s in others.values())


def test_pipeline_trainer_moe_ep_trains_and_matches_replicated():
    """pp×ep MoE-BERT on the 8-device mesh: aux loss decreases, the run
    trains, and the ep-sharded expert compute matches the ep=1 (replicated
    experts) pipeline — the psum over disjoint expert shards is the same
    sum the single-member einsum computes (bf16 reduction order aside)."""
    ds = _copy_task(96)
    kwargs = dict(
        worker_optimizer="adam", learning_rate=3e-3, num_stages=2,
        num_microbatches=2, batch_size=32, num_epoch=3, seed=0,
        aux_loss_weight=0.05,
    )
    t_ep = dk.PipelineTrainer(_moe_model(), ep=2, **kwargs)
    t_ep.train(ds)
    hist_ep = t_ep.get_history()
    assert hist_ep[-1]["loss"] < hist_ep[0]["loss"]
    assert hist_ep[-1]["aux_loss"] < hist_ep[0]["aux_loss"] * 1.05
    assert all(np.isfinite(h["aux_loss"]) for h in hist_ep)

    t_rep = dk.PipelineTrainer(_moe_model(), **kwargs)
    t_rep.train(ds)
    hist_rep = t_rep.get_history()
    # Identical math modulo bf16 reduction grouping: same loss trajectory.
    for a, b in zip(hist_ep, hist_rep):
        assert abs(a["loss"] - b["loss"]) < 5e-2, (a, b)
