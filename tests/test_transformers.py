import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.transformers import (
    DenseTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    OneHotTransformer,
    ReshapeTransformer,
)


def test_one_hot():
    ds = Dataset.from_arrays(label=np.array([0, 2, 1, 2]))
    out = OneHotTransformer(3).transform(ds)
    enc = out["label_encoded"]
    assert enc.shape == (4, 3)
    assert np.array_equal(np.argmax(enc, -1), [0, 2, 1, 2])
    assert np.allclose(enc.sum(-1), 1.0)


def test_one_hot_out_of_range():
    ds = Dataset.from_arrays(label=np.array([0, 5]))
    with pytest.raises(ValueError):
        OneHotTransformer(3).transform(ds)


def test_min_max_explicit_range():
    # Reference semantics: user supplies the data range (e.g. 0..255 images).
    ds = Dataset.from_arrays(features=np.array([[0.0, 127.5, 255.0]]))
    out = MinMaxTransformer(new_min=0.0, new_max=1.0, min=0.0, max=255.0).transform(ds)
    assert np.allclose(out["features_normalized"], [[0.0, 0.5, 1.0]])


def test_min_max_fitted_range_and_custom_target():
    ds = Dataset.from_arrays(features=np.array([[1.0], [3.0], [5.0]]))
    out = MinMaxTransformer(new_min=-1.0, new_max=1.0).transform(ds)
    assert np.allclose(out["features_normalized"], [[-1.0], [0.0], [1.0]])


def test_reshape():
    ds = Dataset.from_arrays(features=np.arange(2 * 784).reshape(2, 784))
    out = ReshapeTransformer("features", "matrix", (28, 28, 1)).transform(ds)
    assert out["matrix"].shape == (2, 28, 28, 1)
    assert np.array_equal(out["matrix"].reshape(2, -1), ds["features"])


def test_dense():
    ds = Dataset.from_arrays(features=np.array([[1, 0], [0, 2]], dtype=np.int64))
    out = DenseTransformer().transform(ds)
    assert out["features_dense"].dtype == np.float32
    assert out["features_dense"].flags["C_CONTIGUOUS"]


def test_label_index_vector():
    ds = Dataset.from_arrays(prediction=np.array([[0.1, 0.7, 0.2], [0.9, 0.05, 0.05]]))
    out = LabelIndexTransformer(3).transform(ds)
    assert np.array_equal(out["prediction_index"], [1.0, 0.0])


def test_label_index_scalar_threshold():
    ds = Dataset.from_arrays(prediction=np.array([0.3, 0.8]))
    out = LabelIndexTransformer().transform(ds)
    assert np.array_equal(out["prediction_index"], [0.0, 1.0])


def test_min_max_per_feature():
    ds = Dataset.from_arrays(
        features=np.array([[0.0, 100.0], [5.0, 300.0], [10.0, 200.0]])
    )
    out = MinMaxTransformer(per_feature=True).transform(ds)
    f = out["features_normalized"]
    np.testing.assert_allclose(f[:, 0], [0.0, 0.5, 1.0])
    np.testing.assert_allclose(f[:, 1], [0.0, 1.0, 0.5])


def test_transformer_pipeline():
    from distkeras_tpu.data.transformers import TransformerPipeline

    ds = Dataset.from_arrays(
        features=np.array([[0.0], [255.0]]), label=np.array([0, 1])
    )
    pipe = TransformerPipeline([
        MinMaxTransformer(min=0.0, max=255.0),
        OneHotTransformer(2),
    ])
    out = pipe.transform(ds)
    assert "features_normalized" in out and "label_encoded" in out


def test_standard_scale():
    from distkeras_tpu.data.transformers import StandardScaleTransformer

    rng = np.random.default_rng(0)
    ds = Dataset.from_arrays(features=(rng.normal(size=(200, 3)) * [1, 10, 100]).astype(np.float32))
    out = StandardScaleTransformer().transform(ds)
    f = out["features_standardized"]
    np.testing.assert_allclose(f.mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(f.std(0), 1.0, atol=1e-3)
