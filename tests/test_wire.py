"""The bin1 binary front door (distkeras_tpu.serving.wire).

Covered here:

- codec round trips (request / token / JSON frames), incremental frame
  decoding across arbitrary read boundaries;
- corrupt and oversized frames fail TYPED (WireError -> bad_request),
  never a hung read;
- ctypes-vs-fallback parity: the native scan/pack core and the pure-
  Python struct path are wire-identical (skips VISIBLY when the .so
  can't be built — CI builds it, so silent rot is impossible);
- protocol negotiation: bin1<->bin1 upgrade, bin1->jsonl downgrade
  against a jsonl-pinned server AND a legacy pre-hello server, strict
  wire="bin1" refusing to downgrade;
- a mixed-protocol fleet (one legacy replica) through the router under
  pipelined load;
- the pooled-connection regression: a replica restarted onto the SAME
  port must never be served by a connection from its previous life.

Everything except the engine-parity test is jax-free (EchoServer).
"""

import asyncio
import json

import pytest

from distkeras_tpu.serving import wire


# -- codecs -----------------------------------------------------------------
def _spec(**over):
    spec = {"prompt": [1, 2, 3, 500], "max_new_tokens": 8,
            "temperature": 0.5, "priority": -1, "timeout": None,
            "speculate": False, "tenant": "acme", "trace_id": "abc-123"}
    spec.update(over)
    return spec


def test_request_roundtrip_all_fields():
    spec = _spec()
    assert wire.decode_request(wire.encode_request(spec)) == spec
    # Defaults: no tenant/trace, timeout set, long prompt (numpy path).
    spec2 = {"prompt": list(range(300)), "max_new_tokens": 2,
             "temperature": 0.0, "priority": 0, "timeout": 12.5,
             "speculate": True}
    assert wire.decode_request(wire.encode_request(spec2)) == spec2


def test_request_length_fields_validated():
    payload = bytearray(wire.encode_request(_spec()))
    with pytest.raises(wire.WireError):
        wire.decode_request(payload[:-1])  # truncated
    with pytest.raises(wire.WireError):
        wire.decode_request(b"\x00" * 4)  # shorter than the header


def test_frame_decoder_incremental_byte_at_a_time():
    spec = _spec()
    frames = (wire.encode_frame(wire.T_REQ, 7, wire.encode_request(spec))
              + wire.encode_token_frame(9, [5, 6, 7])
              + wire.encode_json_frame(wire.T_DONE, 9, {"done": True}))
    dec = wire.FrameDecoder()
    got = []
    for i in range(len(frames)):
        got.extend(dec.feed(frames[i:i + 1]))
    assert [t for t, _, _ in got] == [wire.T_REQ, wire.T_TOK, wire.T_DONE]
    assert wire.decode_request(got[0][2]) == spec
    assert got[1][1] == 9 and wire.decode_tokens(got[1][2]) == [5, 6, 7]
    assert wire.decode_json(got[2][2]) == {"done": True}


def test_corrupt_and_oversized_frames_raise_typed():
    with pytest.raises(wire.WireError):
        # Declared length below the 5-byte type+stream minimum.
        wire.FrameDecoder().feed(b"\x00\x00\x00\x00xxxxxxxx")
    with pytest.raises(wire.WireError):
        # Declared length above max_frame: never buffer toward it.
        wire.FrameDecoder().feed((2 ** 25).to_bytes(4, "little"))


def test_affinity_prefix_clamps_to_prompt():
    """The router's fast-path affinity hash input must cover the PROMPT
    only: a short prompt followed by a per-request trace id must hash
    identically across requests, or cache affinity scatters."""
    a = wire.encode_request(_spec(prompt=[9, 9], trace_id="req-aaaa"))
    b = wire.encode_request(_spec(prompt=[9, 9], trace_id="req-bbbb"))
    assert wire.affinity_prefix(a, 16) == wire.affinity_prefix(b, 16)
    long = wire.encode_request(_spec(prompt=list(range(32))))
    assert len(wire.affinity_prefix(long, 16)) == 64  # 16 ids x 4 bytes
    assert wire.affinity_prefix(b"\x00" * 3, 16) == b""  # malformed


def test_native_python_parity():
    """The ctypes core and the struct fallback must be wire-identical —
    on inputs LARGE enough to actually take the native path (small ones
    deliberately stay in Python; see the crossover constants)."""
    if not wire.native_available():
        pytest.skip("libfastwire.so not built (no C++ toolchain?) — "
                    "native-vs-fallback parity not exercised; CI builds "
                    "native/ so this skip is visible, not silent rot")
    updates = [(i + 1, list(range(i, i + 40))) for i in range(12)]
    native_pack = wire.pack_token_frames(updates)
    stream = native_pack * 8  # > _SMALL_SCAN_BYTES: native scan engages
    native_scan = wire.FrameDecoder().feed(stream)
    lib = wire._LIB
    try:
        wire._LIB = None
        assert wire.pack_token_frames(updates) == native_pack
        assert wire.FrameDecoder().feed(stream) == native_scan
    finally:
        wire._LIB = lib
    assert [(s, wire.decode_tokens(p)) for _, s, p in
            native_scan[:len(updates)]] == updates


# -- negotiation (EchoServer: protocol-complete, engine-free) ---------------
def _echo(wire_mode="auto", echo_tokens=1):
    from distkeras_tpu.serving.cluster.replicas import EchoServer

    return EchoServer(echo_tokens=echo_tokens, wire_mode=wire_mode)


def test_negotiation_upgrade_and_downgrades():
    from distkeras_tpu.serving import ServingClient

    async def go():
        # bin1 <-> bin1
        up = _echo("auto")
        await up.start()
        async with ServingClient("127.0.0.1", up.port,
                                 wire_mode="bin1") as c:
            assert c.proto == "bin1"
            done = await c.generate([42, 1], 1, tenant="t9")
            assert done["tokens"] == [42] and done["tenant"] == "t9"
        # bin1 -> jsonl downgrade: a hello-aware server pinned to jsonl.
        pinned = _echo("jsonl")
        await pinned.start()
        async with ServingClient("127.0.0.1", pinned.port,
                                 wire_mode="auto") as c:
            assert c.proto == "jsonl"
            assert (await c.generate([7, 7], 1))["tokens"] == [7]
        # ...and a LEGACY server that answers hello with its usual
        # unknown-verb bad_request: the downgrade contract.
        legacy = _echo("legacy")
        await legacy.start()
        async with ServingClient("127.0.0.1", legacy.port,
                                 wire_mode="auto") as c:
            assert c.proto == "jsonl"
            assert (await c.generate([9, 9], 1))["tokens"] == [9]
        # Strict wire="bin1" refuses the downgrade with a typed error.
        with pytest.raises(ConnectionError):
            async with ServingClient("127.0.0.1", legacy.port,
                                     wire_mode="bin1"):
                pass
        for s in (up, pinned, legacy):
            await s.stop()

    asyncio.run(go())


def test_bin1_client_reconnects_after_connection_death():
    """A dead bin1 connection must surface as ConnectionError on the
    NEXT call — never a silent hang on a handler nothing will call —
    so the idempotent verbs' reconnect-with-backoff contract engages
    (regression: the demux loop used to die without marking the client
    dead, wedging every later healthz forever)."""
    from distkeras_tpu.serving import ServingClient

    async def go():
        server = _echo()
        await server.start()
        port = server.port
        c = ServingClient("127.0.0.1", port, wire_mode="bin1",
                          max_retries=3, base_delay_s=0.05)
        await c.connect()
        await c.generate([1, 2], 1)
        await server.stop()  # connection dies under the client
        revived = _echo()
        revived._requested_port = port
        await revived.start()
        await asyncio.sleep(0.05)
        # Idempotent verb reconnects transparently...
        h = await asyncio.wait_for(c.healthz(), 10)
        assert h.get("echo") is True
        # ...and streams work on the fresh connection.
        assert (await c.generate([4, 2], 1))["tokens"] == [4]
        await c.aclose()
        await revived.stop()

    asyncio.run(go())


def test_corrupt_frame_is_bad_request_not_a_hung_read():
    """After a negotiated upgrade, garbage bytes must come back as a
    typed bad_request ERR frame and the connection must CLOSE — bounded
    by a timeout, so a regression to a hung read fails the test rather
    than wedging the suite."""

    async def go():
        server = _echo("auto")
        await server.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.port)
        writer.write(wire.hello_line())
        await writer.drain()
        hello = json.loads(await asyncio.wait_for(reader.readline(), 5))
        assert hello["hello"]["proto"] == "bin1"
        # A frame whose declared length is below the legal minimum.
        writer.write(b"\x01\x00\x00\x00garbage")
        await writer.drain()
        data = await asyncio.wait_for(reader.read(65536), 5)
        frames = wire.FrameDecoder().feed(data)
        assert frames and frames[0][0] == wire.T_ERR
        assert wire.decode_json(frames[0][2])["code"] == "bad_request"
        assert await asyncio.wait_for(reader.read(), 5) == b""  # closed
        writer.close()
        await server.stop()

    asyncio.run(go())


def test_mixed_protocol_fleet_under_load():
    """One bin1 replica + one LEGACY jsonl-only replica behind the
    router: a pipelined bin1 client's load completes on BOTH (the mux
    path and the exclusive-jsonl fallback coexist per replica), and the
    negotiated capability is cached per incarnation."""
    from distkeras_tpu.serving import ServingClient
    from distkeras_tpu.serving.cluster.replicas import EchoReplica
    from distkeras_tpu.serving.cluster.router import Router
    from distkeras_tpu.serving.cluster.supervisor import ReplicaSupervisor

    async def go():
        sup = ReplicaSupervisor(
            lambda i: EchoReplica(
                echo_tokens=2,
                wire_mode="auto" if i == 0 else "legacy"),
            2, health_interval_s=5.0)
        await sup.start()
        router = Router(sup, port=0, trace_capacity=0)
        await router.start()
        try:
            async with ServingClient("127.0.0.1", router.port,
                                     wire_mode="bin1") as c:
                assert c.proto == "bin1"
                dones = await asyncio.gather(*(
                    c.generate([i + 1, 5], 1) for i in range(40)))
                assert all(d["tokens"] == [i + 1, i + 1]
                           for i, d in enumerate(dones))
                batch = await c.generate_batch(
                    [[i + 1, 5] for i in range(10)], 1)
                assert all(d["tokens"] == [i + 1, i + 1]
                           for i, d in enumerate(batch))
            # a plain jsonl client rides the same router untouched
            async with ServingClient("127.0.0.1", router.port) as c:
                assert (await c.generate([3, 3], 1))["tokens"] == [3, 3]
            protos = {rid: info.wire_proto
                      for rid, info in sup.replicas.items()}
            assert protos == {"r0": "bin1", "r1": "jsonl"}, protos
            served = {rid: info.handle.server.requests
                      for rid, info in sup.replicas.items()}
            assert all(n > 0 for n in served.values()), served
        finally:
            await router.stop()
            await sup.stop()

    asyncio.run(go())


def test_pooled_conn_not_reused_across_replica_generation():
    """THE regression fix: backend connections are keyed by replica
    INCARNATION, and checkout re-verifies the recorded negotiation
    state — a replica restarted onto the same port can never be served
    by a pooled connection (or a cached protocol capability) from its
    previous life."""
    from distkeras_tpu.serving.cluster.replicas import EchoReplica
    from distkeras_tpu.serving.cluster.router import Router
    from distkeras_tpu.serving.cluster.supervisor import ReplicaSupervisor

    async def go():
        sup = ReplicaSupervisor(lambda i: EchoReplica(),
                                1, health_interval_s=5.0)
        await sup.start()
        router = Router(sup, port=0, trace_capacity=0)
        await router.start()
        try:
            info = sup.replicas["r0"]
            await router._backend_control(info, {"cmd": "healthz"})
            key = (info.rid, info.port, info.generation)
            assert router._pools.get(key), "control conn was not pooled"
            stale = router._pools[key][0]
            # Negotiate the bin1 mux too: both caches must invalidate.
            mux = await router._get_mux(info)
            assert mux is not None and info.wire_proto == "bin1"
            # Simulate a restart that lands on the SAME port: the
            # supervisor bumps the generation and resets the protocol
            # cache (exactly what _start_replica/_restart do).
            info.generation += 1
            info.wire_proto = None
            fresh = await router._acquire(info)
            assert fresh is not stale
            assert fresh.generation == info.generation
            assert stale.writer.is_closing(), \
                "previous-life connection survived the restart"
            assert key not in router._pools and mux.dead, \
                "previous-life pool/mux not pruned"
            # Belt and braces: even a stale conn HANDED BACK after the
            # restart is refused at release.
            router._release(info, stale, healthy=True)
            assert not router._pools.get(
                (info.rid, info.port, info.generation))
            # The new incarnation still serves control verbs.
            rep = await router._backend_control(info, {"cmd": "healthz"})
            assert "healthz" in rep
        finally:
            await router.stop()
            await sup.stop()

    asyncio.run(go())
