"""Tracing/metrics subsystem tests."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.tracing import MetricStream, StepTimer, trace


def test_step_timer_summary():
    t = StepTimer()
    t.start()
    import time

    for _ in range(5):
        time.sleep(0.01)
        t.tick()
    s = t.summary(batch_size=32)
    assert s["steps"] == 4  # skip_warmup=1
    assert s["step_time_mean_s"] > 0.005
    assert "samples_per_sec" in s
    assert s["step_time_var_s2"] >= 0


def test_step_timer_mfu_with_flops():
    t = StepTimer()
    t.start()
    t.tick()
    import time

    time.sleep(0.01)
    t.tick()
    s = t.summary(batch_size=8, flops_per_example=1e9, skip_warmup=1)
    assert "train_tflops_per_sec" in s
    # mfu present only when the device generation is known (not on CPU)
    assert ("mfu" in s) == (jax.devices()[0].platform == "tpu")


def test_metric_stream_records_and_jsonl(tmp_path):
    path = str(tmp_path / "m.jsonl")
    ms = MetricStream.to_jsonl(path)
    ms.emit(0, {"loss": 1.5, "accuracy": np.float32(0.5)})
    ms.emit(1, {"loss": 1.2})
    assert len(ms.records) == 2
    assert ms.last()["loss"] == 1.2
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["step"] == 0 and lines[0]["loss"] == 1.5


def test_profiler_trace_writes(tmp_path):
    log_dir = str(tmp_path / "trace")
    with trace(log_dir):
        _ = jnp.ones((64, 64)) @ jnp.ones((64, 64))
    # jax profiler writes a plugins/profile subtree
    found = []
    for root, _, files in os.walk(log_dir):
        found += files
    assert found, "no trace files written"
