"""Model zoo sanity tests."""

import numpy as np


def test_count_params_bert_base():
    from distkeras_tpu.models.bert import bert_base_mlm

    n = bert_base_mlm(seq_len=16).count_params()
    assert 105e6 < n < 115e6, n  # BERT-base ~109M


def test_count_params_mlp():
    from distkeras_tpu.models import mnist_mlp

    n = mnist_mlp().count_params()
    expected = 785 * 500 + 501 * 300 + 301 * 10
    assert n == expected, (n, expected)


def test_resnet50_flops_and_shapes():
    from distkeras_tpu.models.resnet import resnet50

    m = resnet50(image_size=224)
    assert m.flops_per_example > 8e9  # ~8.2 GFLOPs forward
    n = m.count_params()
    assert 24e6 < n < 27e6, n  # ResNet-50 ~25.6M
