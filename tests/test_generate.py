"""KV-cache autoregressive generation: cached decode must match the
no-cache full-forward rollout token for token."""

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models.bert import gpt_tiny


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=32, vocab_size=64)
    return model, model.init(0)


def _rollout_nocache(model, variables, prompt, n):
    """Reference: full forward each step, argmax next token."""
    toks = np.asarray(prompt, np.int32)
    out = []
    for _ in range(n):
        logits, _ = model.apply(variables, toks)
        nxt = np.argmax(np.asarray(logits, np.float32)[:, -1], axis=-1)
        out.append(nxt.astype(np.int32))
        toks = np.concatenate([toks, nxt[:, None].astype(np.int32)], axis=1)
    return np.stack(out, axis=1)


@pytest.mark.slow
def test_greedy_matches_nocache_rollout(lm, rng):
    model, variables = lm
    prompt = np.asarray(rng.integers(0, 64, size=(2, 5)), np.int32)
    want = _rollout_nocache(model, variables, prompt, 8)
    got = dk.generate(model, variables, prompt, 8, greedy=True)
    np.testing.assert_array_equal(got, want)


def test_sampling_shapes_and_determinism(lm, rng):
    model, variables = lm
    prompt = np.asarray(rng.integers(0, 64, size=(3, 4)), np.int32)
    a = dk.generate(model, variables, prompt, 6, temperature=0.8, top_k=10,
                    seed=7)
    b = dk.generate(model, variables, prompt, 6, temperature=0.8, top_k=10,
                    seed=7)
    c = dk.generate(model, variables, prompt, 6, temperature=0.8, top_k=10,
                    seed=8)
    assert a.shape == (3, 6) and a.dtype == np.int32
    np.testing.assert_array_equal(a, b)  # same seed, same tokens
    assert (a != c).any()  # different seed diverges somewhere
    assert (a >= 0).all() and (a < 64).all()


def test_generator_wrapper_and_single_token(lm, rng):
    model, variables = lm
    gen = dk.Generator(model, variables)
    prompt = np.asarray(rng.integers(0, 64, size=(1, 3)), np.int32)
    out = gen(prompt, 1, greedy=True)
    assert out.shape == (1, 1)
    want = _rollout_nocache(model, variables, prompt, 1)
    np.testing.assert_array_equal(out, want)


def test_generate_rejects_bad_inputs(lm, rng):
    model, variables = lm
    # gpt_tiny(seq_len=32) has cache capacity 64 but TRAINED context 32:
    # the bound is the trained length (untrained pos embeddings past it).
    prompt = np.asarray(rng.integers(0, 64, size=(1, 28)), np.int32)
    with pytest.raises(ValueError, match="trained context"):
        dk.generate(model, variables, prompt, 8)  # 28 + 8 > 32
    with pytest.raises(ValueError, match="top_k"):
        dk.generate(model, variables, prompt[:, :4], 2, top_k=2000)
    with pytest.raises(ValueError, match="top_k"):
        dk.generate(model, variables, prompt[:, :4], 2, top_k=0)
    from distkeras_tpu.models.bert import bert_tiny_mlm

    enc = bert_tiny_mlm(seq_len=16)
    with pytest.raises(ValueError, match="causal"):
        dk.generate(enc, enc.init(0), prompt[:, :4], 2)
    from distkeras_tpu.models.mlp import mnist_mlp

    with pytest.raises(ValueError, match="bert zoo"):
        dk.generate(mnist_mlp(), {}, prompt[:, :4], 2)

@pytest.mark.slow
def test_beam_search_k1_equals_greedy(lm, rng):
    model, variables = lm
    prompt = np.asarray(rng.integers(0, 64, size=(2, 4)), np.int32)
    greedy = dk.generate(model, variables, prompt, 6, greedy=True)
    seqs, scores = dk.beam_search(model, variables, prompt, 6, num_beams=1)
    np.testing.assert_array_equal(seqs[:, 0], greedy)
    assert scores.shape == (2, 1)


@pytest.mark.slow
def test_beam_search_scores_exact_and_sorted(lm, rng):
    """Returned score must equal the true total log-probability of the
    returned sequence (recomputed with no-cache full forwards), and beams
    must be sorted descending; the best beam never scores below greedy."""
    model, variables = lm
    prompt = np.asarray(rng.integers(0, 64, size=(1, 4)), np.int32)
    n, K = 5, 4
    seqs, scores = dk.beam_search(model, variables, prompt, n, num_beams=K)
    assert seqs.shape == (1, K, n) and scores.shape == (1, K)
    assert all(scores[0, i] >= scores[0, i + 1] - 1e-5 for i in range(K - 1))

    def true_logprob(seq):
        toks = prompt.copy()
        total = 0.0
        for t in seq:
            logits, _ = model.apply(variables, toks)
            logp = np.asarray(logits, np.float32)[0, -1]
            logp = logp - np.log(np.exp(logp - logp.max()).sum()) - logp.max()
            total += logp[t]
            toks = np.concatenate([toks, [[t]]], axis=1).astype(np.int32)
        return total

    # Tolerance: the cached decode path and the no-cache forward accumulate
    # bf16 matmul drift differently (~0.05% on a |score| of ~18 here).
    for b in range(K):
        np.testing.assert_allclose(
            true_logprob(seqs[0, b]), scores[0, b], atol=0.05, rtol=2e-3
        )

    greedy = dk.generate(model, variables, prompt, n, greedy=True)
    assert scores[0, 0] >= true_logprob(greedy[0]) - 0.05


@pytest.mark.slow
def test_generate_dp_sharded_matches_unsharded(lm, rng):
    """Batch-parallel decoding on a dp mesh produces the same greedy tokens
    as the single-device path (GSPMD propagates the batch sharding through
    the KV caches)."""
    from distkeras_tpu.parallel.mesh import make_mesh

    model, variables = lm
    prompt = np.asarray(rng.integers(0, 64, size=(8, 4)), np.int32)
    plain = dk.generate(model, variables, prompt, 5, greedy=True)
    mesh = make_mesh({"dp": 8})
    sharded = dk.generate(model, variables, prompt, 5, greedy=True, mesh=mesh)
    np.testing.assert_array_equal(plain, sharded)

    with pytest.raises(ValueError, match="not divisible"):
        dk.generate(model, variables, prompt[:3], 5, greedy=True, mesh=mesh)


def test_generate_with_none_input_shape(lm, rng):
    """Model.input_shape=None (e.g. from_keras without an input shape) must
    fall back to the config's max_seq_len bound, not crash subscripting."""
    import copy

    model, variables = lm
    m2 = copy.copy(model)
    m2.input_shape = None
    prompt = np.asarray(rng.integers(0, 64, size=(2, 4)), np.int32)
    got = dk.generate(m2, variables, prompt, 5, greedy=True)
    want = dk.generate(model, variables, prompt, 5, greedy=True)
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_beam_search_dp_sharded_matches_unsharded(lm, rng):
    """beam_search(mesh=...) mirrors generate's dp batch-parallel contract."""
    from distkeras_tpu.parallel.mesh import make_mesh

    model, variables = lm
    prompt = np.asarray(rng.integers(0, 64, size=(8, 4)), np.int32)
    seqs, scores = dk.beam_search(model, variables, prompt, 4, num_beams=3)
    mesh = make_mesh({"dp": 8})
    s_seqs, s_scores = dk.beam_search(
        model, variables, prompt, 4, num_beams=3, mesh=mesh
    )
    np.testing.assert_array_equal(seqs, s_seqs)
    np.testing.assert_allclose(scores, s_scores, atol=1e-5)

    with pytest.raises(ValueError, match="not divisible"):
        dk.beam_search(model, variables, prompt[:3], 4, num_beams=3, mesh=mesh)


@pytest.mark.slow
def test_generate_from_ring_stripe_trained_weights(rng):
    """Weights trained under sp_impl='ring_stripe' (striped trunk layout)
    are layout-identical to the plain model's — generation through the
    KV-cache decode path (which never stripes; Bert excludes decode from
    the striping bracket) must match the plain model's rollout exactly."""
    import dataclasses

    from distkeras_tpu.models.bert import BertConfig, _make
    from distkeras_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"sp": 4}, devices=None)
    vocab, seq = 64, 32
    cfg = BertConfig(
        vocab_size=vocab, hidden_size=32, num_layers=2, num_heads=2,
        mlp_dim=64, max_seq_len=seq, dropout_rate=0.0, causal=True,
        ring_mesh=mesh, ring_axis="sp", sp_impl="ring_stripe",
    )
    model = _make(cfg, seq, "gpt_stripe_gen")
    import distkeras_tpu as dk

    base = np.arange(512) % vocab
    windows = np.stack([base[i:i + seq] for i in range(128)]).astype(np.int32)
    ds = dk.Dataset.from_arrays(
        features=windows, label=np.roll(windows, -1, axis=1).astype(np.int32)
    )
    t = dk.SynchronousDistributedTrainer(
        model, worker_optimizer="adam", learning_rate=3e-3, batch_size=16,
        num_epoch=3, mesh=make_mesh({"dp": 2, "sp": 4}), shard_sequence=True,
    )
    trained = t.train(ds, shuffle=True)

    prompt = windows[:1, :6]
    got = dk.generate(trained.model, trained.variables, prompt, 8, greedy=True)
    # reference rollout through the PLAIN (no-sp) model on the same weights
    plain = _make(dataclasses.replace(cfg, ring_mesh=None), seq, "gpt_plain_gen")
    want = _rollout_nocache(plain, trained.variables, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert t.get_history()[-1]["loss"] < t.get_history()[0]["loss"]
