"""Tiered fleet-scale KV cache: host-RAM spill tier + fleet directory.

The invariants under test:

- ``HostKVTier`` unit behavior (jax-free): budget + watermark LRU
  eviction, inclusive-cache gets, disk demotion/promotion round trips
  **bitwise**, flush drops entries but keeps lifetime counters;
- **spill → re-admit is bitwise**: blocks evicted from the device pool
  spill to the host tier as exact KVX1 bytes and scatter back H2D on
  the next prefix hit — the re-exported device rows are byte-identical
  to the spilled payloads, greedy output stays token-identical, and an
  ARMED ``RecompileAuditor`` proves decode never retraced (tp-sharded
  pool included);
- a pool-dry admission whose prefix lives in the host tier is served
  from the tier **without preempting** anything;
- router-scheduled **push transfers** (``kv_push``): a real
  prefill+decode fleet with push scheduling stays token-identical under
  armed auditors, the decode side's done record shows the pushed
  arrival, and a repeat request hits the fleet cache **directory**
  (transfer skipped, bytes-saved counted);
- tier-owner death: the supervisor's death callback drops the dead
  replica's directory claims (counted) and the next request falls back
  to monolithic prefill with **zero client-visible errors**;
- a fully-parked tier-pending admission wakes on the scheduler's
  tier-arrival EVENT (no ``pool.version`` polling);
- the tier is observable: gauges/counters in the registry, a
  ``kv_tier`` section in engine debugz, and the debugz text formatter
  renders it.
"""

import asyncio

import numpy as np
import pytest

from distkeras_tpu.serving.kv_tier import HostKVTier

VOCAB = 64
SUP = dict(health_interval_s=0.05, health_timeout_s=2.0, fail_after=2,
           base_delay_s=0.05, max_delay_s=1.0, stable_after_s=0.5)


@pytest.fixture(scope="module")
def lm():
    from distkeras_tpu.models.bert import gpt_tiny

    model = gpt_tiny(seq_len=64, vocab_size=VOCAB)
    return model, model.init(0)


@pytest.fixture
def rng():
    return np.random.default_rng(23)


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


def _ref(lm, prompt, n):
    from distkeras_tpu.inference.generate import generate

    model, variables = lm
    return generate(model, variables, np.asarray([prompt], np.int32),
                    n, greedy=True)[0].tolist()


def _engine(lm, **kw):
    from distkeras_tpu.serving import ServingEngine

    model, variables = lm
    kw.setdefault("slots", 1)
    kw.setdefault("max_queue", 16)
    kw.setdefault("kv_pool_blocks", 5)
    kw.setdefault("kv_block_tokens", 4)
    kw.setdefault("kv_host_tier_mb", 4.0)
    return ServingEngine(model, variables, **kw)


async def _run(engine, coro):
    task = asyncio.create_task(engine.run())
    try:
        return await coro
    finally:
        engine.shutdown(drain=True)
        await task


async def _kv_op(fn, arg):
    event, result = fn(arg)
    await asyncio.wait_for(event.wait(), 30)
    return result


# -- HostKVTier units (jax-free) ---------------------------------------------

def _pay(n, fill):
    return bytes([fill]) * n


def test_tier_budget_watermark_and_inclusive_get():
    tier = HostKVTier(1000, 4, watermark=0.5)
    keys = [[i, i, i, i] for i in range(5)]
    for i, k in enumerate(keys):
        assert tier.put(k, _pay(200, i))
    # 5 x 200 = budget exactly: nothing evicted yet.
    assert tier.stats()["host_entries"] == 5
    # Touch key 0 so it is MRU, then push past the budget: eviction
    # runs down to the 500-byte watermark, keeps the protected insert
    # and the recently-used entry, drops the LRU middle.
    assert tier.get(keys[0]) == _pay(200, 0)
    assert tier.put([9, 9, 9, 9], _pay(200, 9))
    s = tier.stats()
    assert s["host_bytes"] <= 500
    assert tier.contains([9, 9, 9, 9])       # protected insert survives
    assert tier.contains(keys[0])            # MRU survives
    assert not tier.contains(keys[1])        # LRU evicted
    assert s["evictions"] >= 3
    # Inclusive cache: get() leaves the entry resident.
    assert tier.get(keys[0]) == _pay(200, 0)
    assert tier.contains(keys[0])
    # An oversize payload is refused outright, never evicts the world.
    assert not tier.put([8, 8, 8, 8], _pay(2000, 1))
    # probe() counts contiguous complete blocks from the root.
    t2 = HostKVTier(1000, 2)
    t2.put([1, 2], b"a")
    t2.put([1, 2, 3, 4], b"b")
    assert t2.probe([1, 2, 3, 4, 5, 6]) == 2  # third block absent
    assert t2.probe([7, 8, 3, 4]) == 0


def test_tier_disk_demotion_promotion_bitwise_and_flush(tmp_path):
    tier = HostKVTier(400, 4, disk_dir=str(tmp_path),
                      disk_budget_bytes=1000, watermark=0.5)
    blobs = {i: bytes(np.random.default_rng(i).integers(
        0, 256, 150, dtype=np.uint8)) for i in range(4)}
    for i in range(4):
        tier.put([i] * 4, blobs[i])
    s = tier.stats()
    # Crossing 400 bytes demoted LRU entries to disk files.
    assert s["demotions"] >= 1 and s["disk_entries"] >= 1
    assert list(tmp_path.glob("kvx-*.bin"))
    # A disk hit reads back BITWISE and promotes to host RAM.
    demoted = [i for i in range(4) if not tier._host.get(tuple([i] * 4))]
    i = demoted[0]
    assert tier.get([i] * 4) == blobs[i]
    assert tier.stats()["promotions"] == 1
    assert tuple([i] * 4) in tier._host
    # flush() empties both levels, unlinks files, keeps lifetime stats.
    before = tier.stats()
    dropped = tier.flush()
    assert dropped == before["host_entries"] + before["disk_entries"]
    s = tier.stats()
    assert s["host_entries"] == s["disk_entries"] == 0
    assert not list(tmp_path.glob("kvx-*.bin"))
    assert s["demotions"] == before["demotions"]  # counters survive
    assert s["flushes"] == 1


# -- engine level: spill -> re-admit -----------------------------------------

def test_spill_readmit_bitwise_token_identical_armed_auditor(lm, rng):
    """THE tentpole invariant: pool pressure evicts a hot chain to the
    host tier; the next request on that prefix re-admits it H2D and the
    device rows are BITWISE the spilled bytes — token-identical output,
    zero preemptions, and the armed auditor proves decode (and the
    tier's gather/scatter traffic) never retraced it."""
    from distkeras_tpu.serving.kv_transfer import deserialize_blocks
    from distkeras_tpu.telemetry import RecompileAuditor

    auditor = RecompileAuditor()
    # 5 blocks x 4 tokens: a finished 15-token sequence adopts 3
    # blocks, so b's from-scratch admission must evict a's chain.
    engine = _engine(lm, auditor=auditor, arm_auditor_after_warmup=True)
    a, b = _prompt(rng, 11), _prompt(rng, 11)
    wa, wb = _ref(lm, a, 4), _ref(lm, b, 4)

    async def drive():
        outs = [await engine.submit(a, 4).result(),
                await engine.submit(b, 4).result()]
        # a's chain was evicted under b's admission: it lives in the
        # tier now, keyed by full token chains.
        assert engine.metrics.kv_spills >= 2
        tier = engine.kv_tier
        spilled = {k: tier.get(a[:(k + 1) * 4])
                   for k in range(2) if tier.contains(a[:(k + 1) * 4])}
        assert spilled, "nothing of a's chain reached the tier"
        outs.append(await engine.submit(a, 4).result())
        # Re-admitted blocks counted as the prefix hits they are.
        assert engine.metrics.kv_readmits >= 1
        assert engine.kv_pool.hit_tokens >= 4
        assert engine.metrics.preemptions == 0
        # Bitwise: export a's device-resident chain and compare each
        # re-admitted block's rows against its spilled payload.
        res = await _kv_op(engine.request_kv_export, a)
        _, ex_leaves = deserialize_blocks(res["payload"])
        for k, payload in spilled.items():
            _, sp_leaves = deserialize_blocks(payload)
            for sp, ex in zip(sp_leaves, ex_leaves):
                assert sp[0].tobytes() == ex[k].tobytes()
        return outs

    outs = asyncio.run(_run(engine, drive()))
    assert outs == [wa, wb, wa]
    assert auditor.compiles("serving_decode") == 1
    assert auditor.report()["serving_decode"]["armed"]


def test_sharded_pool_spill_readmit_round_trip(lm, rng):
    """The tier under a tp=2 pool: spilled payloads carry full heads
    (the kv_transfer contract), re-admission reshards on upload, and
    greedy output stays token-identical."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices for tp=2")
    from distkeras_tpu.parallel.mesh import serving_mesh

    engine = _engine(lm, mesh=serving_mesh({"tp": 2},
                                           devices=jax.devices()[:2]))
    a, b = _prompt(rng, 11), _prompt(rng, 11)
    wa, wb = _ref(lm, a, 4), _ref(lm, b, 4)

    async def drive():
        outs = [await engine.submit(a, 4).result(),
                await engine.submit(b, 4).result()]
        assert engine.metrics.kv_spills >= 1
        outs.append(await engine.submit(a, 4).result())
        assert engine.metrics.kv_readmits >= 1
        return outs

    assert asyncio.run(_run(engine, drive())) == [wa, wb, wa]


def test_pool_dry_admission_served_from_tier_without_preemption(lm, rng):
    """A request whose prefix sits in the host tier must be served by
    re-admission (adopt + H2D scatter), never by preempting running
    slots — adoption only reclaims unreferenced leaves."""
    engine = _engine(lm)
    a = _prompt(rng, 11)
    fillers = [_prompt(rng, 11) for _ in range(2)]

    async def drive():
        outs = [await engine.submit(a, 4).result()]
        for f in fillers:  # churn the pool dry of a's chain
            outs.append(await engine.submit(f, 4).result())
        outs.append(await engine.submit(a, 4).result())
        return outs

    outs = asyncio.run(_run(engine, drive()))
    want = [_ref(lm, a, 4)] + [_ref(lm, f, 4) for f in fillers]
    assert outs == want + [want[0]]
    assert engine.metrics.kv_readmits >= 1
    assert engine.metrics.kv_readmit_bytes > 0
    assert engine.metrics.preemptions == 0


def test_tier_flushes_on_weight_swap(lm, rng):
    """KV is a pure function of (weights, tokens): a weight swap must
    flush the host tier with the device pool — stale spilled bytes
    would poison every later re-admit."""
    import jax

    model, variables = lm
    engine = _engine(lm)
    prompt = _prompt(rng, 11)

    async def drive():
        await engine.submit(prompt, 4).result()
        await engine.submit(_prompt(rng, 11), 4).result()  # force spill
        assert engine.kv_tier.stats()["host_entries"] > 0
        new = jax.tree.map(lambda x: x, variables)
        event, result = engine.request_param_swap(new)
        await asyncio.wait_for(event.wait(), 30)
        assert "error" not in result
        assert engine.kv_tier.stats()["host_entries"] == 0
        assert engine.kv_tier.stats()["flushes"] == 1
        # Post-swap service is correct (re-prefill, no stale bytes).
        return await engine.submit(prompt, 4).result()

    assert asyncio.run(_run(engine, drive())) == _ref(lm, prompt, 4)


# -- scheduler: tier-arrival event (no pool.version polling) -----------------

def test_parked_tier_pending_wakes_on_kv_arrival_event():
    from distkeras_tpu.serving.scheduler import Scheduler

    async def main():
        sched = Scheduler(max_depth=4)
        waiter = asyncio.create_task(sched.wait_for_kv_arrival(5.0))
        await asyncio.sleep(0.01)
        assert not waiter.done()
        # A plain kick targets the request-arrival path, NOT the tier
        # event: the parked tier-pending head must not thundering-herd
        # on every wake.
        sched.kick()
        await asyncio.sleep(0.01)
        assert not waiter.done()
        t0 = asyncio.get_running_loop().time()
        sched.note_kv_arrival()
        assert await waiter is True
        assert asyncio.get_running_loop().time() - t0 < 1.0
        # note_kv_arrival also wakes the generic wake path (a parked
        # NON-tier head must see freed blocks from a spill-evict too).
        waiter2 = asyncio.create_task(sched.wait_for_wake(5.0))
        await asyncio.sleep(0.01)
        sched.note_kv_arrival()
        await asyncio.wait_for(waiter2, 1.0)

    asyncio.run(main())


# -- fleet: push scheduling + directory --------------------------------------

def _roles_cluster(lm, roles, registry=None, auditors=None,
                   router_kwargs=None, **engine_kw):
    from distkeras_tpu.serving import LocalReplica, ServingCluster
    from distkeras_tpu.telemetry import RecompileAuditor

    def factory(i):
        def build():
            kw = dict(slots=2, kv_pool_blocks=64, kv_block_tokens=4,
                      kv_host_tier_mb=4.0)
            kw.update(engine_kw)
            if auditors is not None:
                auditors[i] = RecompileAuditor()
                kw.update(auditor=auditors[i],
                          arm_auditor_after_warmup=True)
            return _engine(lm, max_queue=16, **kw)

        return LocalReplica(build)

    kwargs = {"affinity_tokens": 4, "min_handoff_tokens": 4}
    kwargs.update(router_kwargs or {})
    return ServingCluster(factory, len(roles), roles=roles,
                          registry=registry, supervisor_kwargs=SUP,
                          router_kwargs=kwargs)


def test_push_scheduled_transfer_token_identical_and_directory_hit(
        lm, rng):
    """Push mode end to end on REAL engines: the router schedules a
    P→D push after the handoff, the decode replica parks on kv_wait
    until the pushed import lands (no pull), output is token-identical
    under armed auditors — and the SAME family's next request skips the
    transfer entirely via the fleet cache directory."""
    from distkeras_tpu.serving import ServingClient
    from distkeras_tpu.telemetry import MetricsRegistry

    async def main():
        registry = MetricsRegistry()
        auditors = {}
        cluster = _roles_cluster(lm, ["prefill", "decode"],
                                 registry=registry, auditors=auditors,
                                 router_kwargs={"kv_push": True})
        prompt = _prompt(rng, 12)
        ref = _ref(lm, prompt, 6)
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port,
                                     wire_mode="auto") as c:
                done = await c.generate(prompt, 6)
                assert done["tokens"] == ref
                km = done.get("kv_migration") or {}
                assert km.get("pushed") is True, km
                assert "fallback" not in km
                done2 = await c.generate(prompt, 6)
                assert done2["tokens"] == ref
            snap = registry.snapshot()
            assert snap["router_kv_pushes_total"]["value"] >= 1
            assert snap["router_kv_push_fallbacks_total"]["value"] == 0
            assert snap["router_kv_push_bytes_total"]["value"] > 0
            # Second request: directory found the decode replica
            # already holding the family — transfer skipped, counted.
            assert snap["router_kv_directory_hits_total"]["value"] >= 1
            assert snap["router_kv_push_bytes_saved_total"]["value"] > 0
            for rid, info in cluster.replicas.items():
                assert info.handle.engine.decode_compile_count() in (
                    0, 1), rid
            stats = cluster.router.kv_directory_stats()
            assert stats["families"] >= 1 and stats["holders"] >= 2

    asyncio.run(main())


def test_tier_owner_death_counted_fallback_zero_client_errors():
    """Kill the directory's tier owner (the prefill replica) between
    requests: its directory claims drop via the supervisor death
    callback (counted), and the next request completes by monolithic
    re-prefill — a counted fallback, never a client-visible error."""
    from distkeras_tpu.serving import ServingClient, ServingCluster
    from distkeras_tpu.serving.cluster.replicas import EchoReplica
    from distkeras_tpu.telemetry import MetricsRegistry

    async def _wait_until(cond, timeout=30.0, what="condition"):
        t0 = asyncio.get_running_loop().time()
        while not cond():
            if asyncio.get_running_loop().time() - t0 > timeout:
                raise AssertionError(f"timed out waiting for {what}")
            await asyncio.sleep(0.02)

    async def main():
        registry = MetricsRegistry()
        cluster = ServingCluster(
            lambda i: EchoReplica(kv_block_tokens=4),
            2, roles=["prefill", "decode"], registry=registry,
            supervisor_kwargs=SUP,
            router_kwargs={"affinity_tokens": 4,
                           "min_handoff_tokens": 4, "kv_push": True})
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port,
                                     wire_mode="auto") as c:
                done = await c.generate([5, 6, 7, 8, 9], 1)
                assert done["tokens"] == [5]
            await _wait_until(
                lambda: cluster.router.kv_directory_stats()[
                    "families"] >= 1, what="directory entry")
            # Hard-kill the tier owner; the supervisor's death callback
            # must invalidate its directory claims.
            await cluster.replicas["r0"].handle.kill()
            await _wait_until(
                lambda: cluster.router.kv_directory_stats()[
                    "families"] == 0, what="directory invalidation")
            assert registry.snapshot()[
                "router_kv_directory_evictions_total"]["value"] >= 1
            # Requests keep completing: handoff (and push) fall back to
            # monolithic echo while the owner is down or restarting.
            async with ServingClient("127.0.0.1", cluster.port,
                                     wire_mode="auto") as c:
                for _ in range(3):
                    done = await c.generate([5, 6, 7, 8, 9], 1)
                    assert done["tokens"] == [5]
                    assert "error" not in done

    asyncio.run(main())


def test_directory_steering_picks_holder_and_counts(rng):
    """Capacity-aware directory steering on an echo fleet: with TWO
    decode replicas, the first request's push records its decode pick
    as the family's holder; the SAME family's next dispatches must be
    steered back to that holder (router_kv_dir_steered_total counts
    them) instead of round-robining least-outstanding — and every one
    of them rides the directory hit (transfer skipped)."""
    from distkeras_tpu.serving import ServingClient, ServingCluster
    from distkeras_tpu.serving.cluster.replicas import EchoReplica
    from distkeras_tpu.telemetry import MetricsRegistry

    async def _wait_until(cond, timeout=30.0, what="condition"):
        t0 = asyncio.get_running_loop().time()
        while not cond():
            if asyncio.get_running_loop().time() - t0 > timeout:
                raise AssertionError(f"timed out waiting for {what}")
            await asyncio.sleep(0.02)

    async def main():
        registry = MetricsRegistry()
        cluster = ServingCluster(
            lambda i: EchoReplica(kv_block_tokens=4),
            3, roles=["prefill", "decode", "decode"], registry=registry,
            supervisor_kwargs=SUP,
            router_kwargs={"affinity_tokens": 4,
                           "min_handoff_tokens": 4, "kv_push": True})
        prompt = [5, 6, 7, 8, 9]
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port,
                                     wire_mode="auto") as c:
                done = await c.generate(prompt, 1)
                assert done["tokens"] == [5]
                # The push must land (directory holder recorded) before
                # the steered requests go out.
                await _wait_until(
                    lambda: cluster.router.kv_directory_stats()[
                        "holders"] >= 2, what="push-recorded holder")
                for _ in range(3):
                    done = await c.generate(prompt, 1)
                    assert done["tokens"] == [5]
            snap = registry.snapshot()
            assert snap["router_kv_dir_steered_total"]["value"] >= 3
            assert snap["router_kv_directory_hits_total"]["value"] >= 3
            stats = cluster.router.kv_directory_stats()
            assert stats["directory_steered"] >= 3
        # The capacity gate: a holder whose healthz shows an exhausted
        # pool is NOT steerable (it would preempt the very blocks we
        # steered for); an unreported pool stays capacious.
        router = cluster.router
        info = next(iter(cluster.replicas.values()))
        info.last_health = {"kv_pool": {"blocks_free": 0}}
        assert router._kv_headroom(info) is False
        info.last_health = {"kv_pool": {"blocks_free": 3}}
        assert router._kv_headroom(info) is True
        info.last_health = {}
        assert router._kv_headroom(info) is True

    asyncio.run(main())


# -- observability ------------------------------------------------------------

def test_tier_observability_debugz_and_registry(lm, rng):
    from distkeras_tpu.serving.debugz import format_debugz

    engine = _engine(lm)
    a, b = _prompt(rng, 11), _prompt(rng, 11)

    async def drive():
        await engine.submit(a, 4).result()
        await engine.submit(b, 4).result()
        await engine.submit(a, 4).result()
        return engine.debugz()

    dz = asyncio.run(_run(engine, drive()))
    kt = dz["kv_tier"]
    assert kt["spills"] >= 1 and kt["spill_bytes"] > 0
    assert kt["readmits"] >= 1 and kt["readmit_bytes"] > 0
    assert kt["host_budget_bytes"] == 4 * 2 ** 20
    assert kt["resident_bytes"] >= 0
    snap = engine.metrics.registry.snapshot()
    for name in ("kv_tier_host_bytes", "kv_tier_host_entries",
                 "kv_tier_resident_bytes", "kv_tier_hits_total",
                 "kv_tier_spills_total", "kv_tier_readmits_total",
                 "kv_pushes_total"):
        assert name in snap, name
    assert snap["kv_tier_spills_total"]["value"] >= 1
    text = format_debugz(dz)
    assert "kv_tier:" in text and "kv_tier_traffic:" in text
