"""Ring flash attention: flash kernels per hop + exact logsumexp merge.
Forward and gradients verified against dense attention, causal and not."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.ops.ring_flash import ring_flash_attention
from distkeras_tpu.parallel.mesh import make_mesh


def _qkv(rng, B=2, S=64, H=2, D=8):
    mk = lambda: np.asarray(rng.normal(size=(B, S, H, D)), np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(rng, causal):
    q, k, v = _qkv(rng)
    mesh = make_mesh({"dp": 2, "sp": 4})
    out = ring_flash_attention(q, k, v, mesh, seq_axis="sp", causal=causal,
                               block_q=8)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gradients_match_dense(rng, causal):
    q, k, v = _qkv(rng, B=1, S=32, H=1, D=8)
    mesh = make_mesh({"sp": 8})

    def loss_ring(q, k, v):
        return jnp.mean(
            ring_flash_attention(q, k, v, mesh, seq_axis="sp", causal=causal,
                                 block_q=4) ** 2
        )

    def loss_dense(q, k, v):
        return jnp.mean(dot_product_attention(q, k, v, causal=causal) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-3)


def test_flash_return_lse_matches_manual(rng):
    from distkeras_tpu.ops.pallas.flash_attention import flash_attention

    q, k, v = _qkv(rng, B=1, S=32, H=1, D=8)
    out, lse = flash_attention(q, k, v, block_q=16, block_k=16, return_lse=True)
    # manual logsumexp of scores
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(8)
    ref_lse = np.log(np.exp(scores - scores.max(-1, keepdims=True)).sum(-1)) + scores.max(-1)
    np.testing.assert_allclose(
        np.asarray(lse)[0, :, 0], ref_lse[0, 0], atol=1e-4, rtol=1e-4
    )
