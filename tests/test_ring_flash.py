"""Ring flash attention: flash kernels per hop + exact logsumexp merge.
Forward and gradients verified against dense attention, causal and not."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.ops.ring_flash import ring_flash_attention
from distkeras_tpu.parallel.mesh import make_mesh


def _qkv(rng, B=2, S=64, H=2, D=8):
    mk = lambda: np.asarray(rng.normal(size=(B, S, H, D)), np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_ring_flash_matches_dense(rng, causal):
    q, k, v = _qkv(rng)
    mesh = make_mesh({"dp": 2, "sp": 4})
    out = ring_flash_attention(q, k, v, mesh, seq_axis="sp", causal=causal,
                               block_q=8)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_ring_flash_gradients_match_dense(rng, causal):
    q, k, v = _qkv(rng, B=1, S=32, H=1, D=8)
    mesh = make_mesh({"sp": 8})

    def loss_ring(q, k, v):
        return jnp.mean(
            ring_flash_attention(q, k, v, mesh, seq_axis="sp", causal=causal,
                                 block_q=4) ** 2
        )

    def loss_dense(q, k, v):
        return jnp.mean(dot_product_attention(q, k, v, causal=causal) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-3)


def test_flash_return_lse_matches_manual(rng):
    from distkeras_tpu.ops.pallas.flash_attention import flash_attention

    q, k, v = _qkv(rng, B=1, S=32, H=1, D=8)
    out, lse = flash_attention(q, k, v, block_q=16, block_k=16, return_lse=True)
    # manual logsumexp of scores
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(8)
    ref_lse = np.log(np.exp(scores - scores.max(-1, keepdims=True)).sum(-1)) + scores.max(-1)
    np.testing.assert_allclose(
        np.asarray(lse)[0, :, 0], ref_lse[0, 0], atol=1e-4, rtol=1e-4
    )


@pytest.mark.slow
@pytest.mark.parametrize("sp_impl", ["ring", "ring_stripe"])
def test_bert_with_ring_attention_trains(rng, sp_impl):
    """BERT with ring-flash attention trains under the sync trainer on a
    dp x sp mesh — end-to-end sequence-parallel long-context training.
    ring_stripe additionally pins the model-level stripe/unstripe
    bracketing: logits must equal the plain dense model's."""
    import dataclasses

    import distkeras_tpu as dk
    from distkeras_tpu.models import bert as bert_mod

    mesh = make_mesh({"dp": 2, "sp": 4})
    vocab, seq = 64, 32
    cfg = bert_mod.BertConfig(
        vocab_size=vocab, hidden_size=64, num_layers=2, num_heads=2,
        mlp_dim=128, max_seq_len=seq, dropout_rate=0.0,
        ring_mesh=mesh, ring_axis="sp", sp_impl=sp_impl,
        causal=(sp_impl == "ring_stripe"),  # stripe is causal-only
    )
    model = bert_mod._make(cfg, seq, f"bert_{sp_impl}")

    tokens = np.asarray(rng.integers(1, vocab, size=(128, seq)), np.int32)
    ds = dk.Dataset.from_arrays(features=tokens, label=tokens)
    trainer = dk.SynchronousDistributedTrainer(
        model, worker_optimizer="adam", learning_rate=1e-3,
        batch_size=8, num_epoch=2, mesh=mesh, shard_sequence=True,
    )
    trainer.train(ds)
    hist = trainer.get_history()
    assert hist[-1]["loss"] < hist[0]["loss"]

    # correctness: sp model forward == plain model forward (same weights)
    plain_cfg = dataclasses.replace(cfg, ring_mesh=None)
    plain = bert_mod._make(plain_cfg, seq, f"bert_plain_{sp_impl}")
    variables = model.init(3)
    x = tokens[:4]
    o_ring, _ = model.apply(variables, x)
    o_plain, _ = plain.apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(o_ring), np.asarray(o_plain), atol=3e-2, rtol=3e-2
    )


def test_ring_flash_non_divisible_block(rng):
    """s_local=24 with default-ish block 16 -> fitted divisor; no dropped
    tail rows (regression for the silent floor-division bug)."""
    q, k, v = _qkv(rng, B=1, S=48, H=1, D=8)
    mesh = make_mesh({"sp": 2})
    out = ring_flash_attention(q, k, v, mesh, seq_axis="sp", block_q=16)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


def test_stripe_shard_roundtrip_and_layout():
    from distkeras_tpu.ops.ring_flash import stripe_shard, stripe_unshard

    x = np.arange(2 * 12 * 3).reshape(2, 12, 3).astype(np.float32)
    s = np.asarray(stripe_shard(x, 4))
    # contiguous shard m (rows m*3..m*3+2 of the striped layout) holds
    # tokens m, m+4, m+8
    for m in range(4):
        np.testing.assert_array_equal(
            s[:, m * 3:(m + 1) * 3], x[:, m::4]
        )
    np.testing.assert_array_equal(np.asarray(stripe_unshard(s, 4)), x)
    with pytest.raises(ValueError, match="divisible"):
        stripe_shard(x, 5)


def test_striped_ring_flash_matches_dense_causal(rng):
    """Striped layout (balanced causal ring): stripe -> ring -> unstripe
    equals dense causal attention on the natural order."""
    from distkeras_tpu.ops.ring_flash import stripe_shard, stripe_unshard

    q, k, v = _qkv(rng)
    p = 4
    mesh = make_mesh({"dp": 2, "sp": p})
    qs, ks, vs = (stripe_shard(t, p) for t in (q, k, v))
    out = ring_flash_attention(qs, ks, vs, mesh, seq_axis="sp", causal=True,
                               block_q=8, stripe=True)
    out = stripe_unshard(out, p)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)
    with pytest.raises(ValueError, match="causal"):
        ring_flash_attention(qs, ks, vs, mesh, seq_axis="sp", causal=False,
                             stripe=True)


@pytest.mark.slow
def test_striped_ring_flash_gradients_match_dense(rng):
    from distkeras_tpu.ops.ring_flash import stripe_shard, stripe_unshard

    q, k, v = _qkv(rng, B=1, S=32, H=1, D=8)
    p = 8
    mesh = make_mesh({"sp": p})
    # weight the loss per natural-order token so a layout bug cannot cancel
    w = np.asarray(np.linspace(0.5, 1.5, 32), np.float32)[None, :, None, None]

    def loss_ring(q, k, v):
        o = ring_flash_attention(
            stripe_shard(q, p), stripe_shard(k, p), stripe_shard(v, p),
            mesh, seq_axis="sp", causal=True, block_q=4, stripe=True,
        )
        return jnp.mean((stripe_unshard(o, p) * w) ** 2)

    def loss_dense(q, k, v):
        return jnp.mean((dot_product_attention(q, k, v, causal=True) * w) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   atol=2e-3, rtol=2e-3)


def test_striped_jnp_ring_matches_dense_causal(rng):
    """Same layout through the jnp online-softmax ring (attention.py)."""
    from distkeras_tpu.ops.attention import ring_self_attention
    from distkeras_tpu.ops.ring_flash import stripe_shard, stripe_unshard

    q, k, v = _qkv(rng)
    p = 4
    mesh = make_mesh({"dp": 2, "sp": p})
    qs, ks, vs = (stripe_shard(t, p) for t in (q, k, v))
    out = ring_self_attention(qs, ks, vs, mesh, seq_axis="sp", causal=True,
                              stripe=True)
    out = stripe_unshard(out, p)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)

def test_ring_attention_direct_call_rejects_noncausal_stripe():
    """The shard_map-level ring_attention (ops/attention.py) validates
    stripe=True + causal=False at function entry — before any mesh-axis
    lookup — so a direct SPMD caller gets a loud contract error instead
    of contiguous causal semantics silently applied to striped inputs.
    Callable with plain arrays precisely because the check fires before
    lax.axis_index would demand a real named axis."""
    from distkeras_tpu.ops.attention import ring_attention

    q = k = v = np.zeros((1, 4, 1, 4), np.float32)
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, k, v, axis_name="sp", causal=False, stripe=True)


def test_ring_stripe_rejections():
    """Loud failures for the striped layout's contract edges: non-causal
    stripe, and sequence parallelism inside the pipelined trunk (where the
    model-level striping cannot run and masks would be silently wrong)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models import bert as bert_mod

    mesh = make_mesh({"sp": 4})
    cfg = bert_mod.BertConfig(
        vocab_size=32, hidden_size=16, num_layers=2, num_heads=2,
        mlp_dim=32, max_seq_len=8, ring_mesh=mesh, ring_axis="sp",
        sp_impl="ring_stripe", causal=False,
    )
    with pytest.raises(ValueError, match="causal"):
        bert_mod._make(cfg, 8, "stripe_noncausal").init(0)

    import dataclasses

    cfg2 = dataclasses.replace(cfg, causal=True)
    with pytest.raises(ValueError, match="pipelined trunk"):
        dk.PipelineTrainer(bert_mod._make(cfg2, 8, "stripe_pipe"),
                           num_stages=2)
