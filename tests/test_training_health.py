"""Training-run observability (telemetry.training_health + .device).

What's under test, all CPU:

- staleness accounting against a SCRIPTED commit sequence: known lags
  in, known percentiles/buckets/goodput out (DynSGD's damping is the
  goodput definition, so the numbers are exact);
- EASGD divergence gauge parity with a hand-computed L2;
- duplicate/pull/rebase bookkeeping and the per-worker statusz table;
- the typed device-memory sentinel: "backend has no memory_stats" is
  ``available=False`` with None bytes — never a lying 0 — and the
  trainers' device-cache budget falls back accordingly;
- a REAL multi-worker async run (DOWNPOUR and AEASGD, 2 workers)
  producing per-worker staleness percentiles and (elastic) divergence
  in statusz, rendered by ``format_statusz`` and dumped into
  ``artifact_dir`` so a red run ships its worker table;
- the deprecated ``tracing.trace`` shim forwards to the promoted
  ``telemetry.profile_trace`` with its DeprecationWarning intact.
"""

import json
import math

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.parallel.protocols import (
    AEASGDProtocol,
    ADAGProtocol,
    DynSGDProtocol,
)
from distkeras_tpu.parallel.ps import ParameterServerService
from distkeras_tpu.serving.debugz import format_statusz
from distkeras_tpu.telemetry import MetricsRegistry, TrainingHealth


def _tree(val, n=4):
    return {"w": np.full(n, val, np.float32)}


# -- scripted staleness / goodput --------------------------------------------

def test_staleness_histogram_matches_scripted_commits():
    """Known lags -> known staleness samples, buckets, and goodput.
    Commit k is applied when the PS counter reads k, with
    ``last_update = k - lag_k`` -> staleness = lag_k exactly."""
    reg = MetricsRegistry()
    health = TrainingHealth(registry=reg, num_workers=2, protocol="dynsgd")
    svc = ParameterServerService(
        DynSGDProtocol(), _tree(0.0), 2, registry=reg, health=health)
    svc.start()
    client = svc.client()
    lags = [0, 0, 1, 3, 2, 5]
    try:
        for k, lag in enumerate(lags):
            client.commit_pull({
                "delta": _tree(1.0),  # ||ones(4)|| = 2.0
                "last_update": k - lag,
                "worker": k % 2,
                "commit_id": f"w{k % 2}:{k}",
            })
    finally:
        svc.stop()

    sz = health.statusz()
    assert sz["staleness"]["samples"] == len(lags)
    assert sz["staleness"]["max"] == max(lags)
    from distkeras_tpu.telemetry import percentile

    assert sz["staleness"]["p50"] == pytest.approx(
        percentile(lags, 50))
    assert sz["staleness"]["p99"] == pytest.approx(
        percentile(lags, 99), abs=0.01)

    # Goodput: raw mass = 2.0 per commit; applied mass damped by the
    # SAME 1/(staleness+1) DynSGD applies to the center.
    raw = 2.0 * len(lags)
    applied = sum(2.0 / (lag + 1) for lag in lags)
    assert sz["goodput"]["update_mass"] == pytest.approx(raw)
    assert sz["goodput"]["applied_mass"] == pytest.approx(applied, rel=1e-5)
    assert sz["goodput"]["ratio"] == pytest.approx(applied / raw, rel=1e-5)

    # Registry histogram: cumulative counts land in the right buckets,
    # and the worst-sample exemplar names the worker that committed it.
    snap = reg.snapshot()
    hist = snap["train_commit_staleness"]
    assert hist["count"] == len(lags)
    ex = reg.histogram("train_commit_staleness").exemplars()
    worst_worker = lags.index(max(lags)) % 2
    assert any(v["trace_id"] == f"worker:{worst_worker}"
               for v in ex.values())
    # Per-worker table: both workers committed, ages recorded.
    workers = {w["worker"]: w for w in sz["workers"]}
    assert workers[0]["commits"] == 3 and workers[1]["commits"] == 3
    assert all(w["last_commit_age_s"] is not None for w in sz["workers"])
    assert sz["ps"]["num_commits"] == len(lags)


def test_duplicate_commits_counted_per_worker():
    health = TrainingHealth(num_workers=1, protocol="dynsgd")
    svc = ParameterServerService(
        DynSGDProtocol(), _tree(0.0), 1, health=health)
    svc.start()
    client = svc.client()
    try:
        payload = {"delta": _tree(1.0), "last_update": 0,
                   "worker": 0, "commit_id": "w0:1"}
        client.commit_pull(payload)
        client.commit_pull(payload)  # retried commit: deduped
    finally:
        svc.stop()
    w = health.statusz()["workers"][0]
    assert w["commits"] == 1 and w["duplicates"] == 1


def test_adag_goodput_uses_one_over_n():
    health = TrainingHealth(num_workers=4, protocol="adag")
    svc = ParameterServerService(
        ADAGProtocol(), _tree(0.0), 4, health=health)
    svc.start()
    try:
        svc.client().commit_pull({"delta": _tree(1.0), "last_update": 0,
                                  "worker": 0, "commit_id": "w0:1"})
    finally:
        svc.stop()
    assert health.goodput_ratio == pytest.approx(0.25)


def test_worker_identity_falls_back_to_commit_id():
    """The gRPC wire drops the ``worker`` field; the stamped commit_id
    (``w<idx>:<counter>``) still attributes the commit."""
    assert TrainingHealth.worker_of({"commit_id": "w3:17"}) == 3
    assert TrainingHealth.worker_of({"worker": 5, "commit_id": "w3:1"}) == 5
    assert TrainingHealth.worker_of({"commit_id": "nonsense"}) is None


# -- EASGD divergence ---------------------------------------------------------

def test_easgd_divergence_matches_hand_computed_l2():
    """One elastic exchange from local params a known offset away from
    the center: the recorded divergence IS ||local - center||_2."""
    rho, lr = 5.0, 0.1
    protocol = AEASGDProtocol(rho=rho, learning_rate=lr)
    health = TrainingHealth(num_workers=1, protocol="aeasgd")
    center = {"a": np.zeros(3, np.float32), "b": np.ones(2, np.float32)}
    svc = ParameterServerService(protocol, center, 1, health=health)
    svc.start()
    client = svc.client()
    try:
        _, carry = protocol.worker_begin(client, None)
        local = {"a": np.array([3.0, 0.0, 4.0], np.float32),
                 "b": np.array([1.0, 2.0], np.float32)}
        protocol.worker_window(local, carry, client)
    finally:
        svc.stop()
    # offset: a = [3,0,4] (norm 5), b - center_b = [0,1] (norm 1)
    want = math.sqrt(5.0**2 + 1.0**2)
    assert health.divergence == pytest.approx(want, rel=1e-6)
    # The applied force's mass is alpha * divergence.
    sz = health.statusz()
    assert sz["goodput"]["update_mass"] == pytest.approx(
        rho * lr * want, rel=1e-5)
    assert sz["workers"][0]["divergence"] == pytest.approx(want, rel=1e-5)


# -- device-memory sentinel ---------------------------------------------------

class _DevNoStats:
    platform = "fake"
    id = 0


class _DevRaises:
    platform = "fake"
    id = 1

    def memory_stats(self):
        raise NotImplementedError("no stats on this backend")


class _DevWithStats:
    platform = "fake"
    id = 2

    def memory_stats(self):
        return {"bytes_in_use": 10, "bytes_limit": 100,
                "peak_bytes_in_use": 50}


def test_device_memory_typed_sentinel_vs_zero():
    from distkeras_tpu.telemetry import device_memory

    for dev in (_DevNoStats(), _DevRaises()):
        mem = device_memory(dev)
        assert mem.available is False
        # "No data" is None, NEVER 0 bytes.
        assert mem.bytes_in_use is None and mem.bytes_limit is None
        assert mem.headroom_bytes is None
    mem = device_memory(_DevWithStats())
    assert mem.available and mem.bytes_in_use == 10
    assert mem.headroom_bytes == 90


def test_memory_gauges_distinguish_unavailable():
    from distkeras_tpu.telemetry import publish_memory_gauges

    reg = MetricsRegistry()
    publish_memory_gauges(reg, devices=[_DevNoStats(), _DevWithStats()],
                          params_bytes=123)
    snap = reg.snapshot()
    assert snap['device_memory_stats_available{device=fake:0}'][
        "value"] == 0.0
    assert snap['device_memory_stats_available{device=fake:2}'][
        "value"] == 1.0
    # The blind device publishes NO byte series at all.
    assert 'device_bytes_in_use{device=fake:0}' not in snap
    assert snap['device_bytes_in_use{device=fake:2}']["value"] == 10
    assert snap["model_params_bytes"]["value"] == 123


def test_device_cache_budget_uses_sentinel_fallback():
    trainer = dk.DOWNPOUR(_model(), num_workers=1)
    # No stats -> the conservative constant, not a budget from fake 0s.
    assert (trainer._device_cache_budget(_DevNoStats(), 10)
            == trainer._DEVICE_CACHE_LIMIT)
    assert (trainer._device_cache_budget(_DevRaises(), 10)
            == trainer._DEVICE_CACHE_LIMIT)
    # Real stats -> limit - 3*state - limit/4.
    assert trainer._device_cache_budget(_DevWithStats(), 10) == \
        max(0, 100 - 30 - 25)


# -- real multi-worker runs ---------------------------------------------------

def _model(input_dim=16, classes=2):
    return Model.from_flax(
        MLP(features=(32,), num_classes=classes),
        input_shape=(input_dim,),
        output_dim=classes,
    )


def test_downpour_statusz_on_real_two_worker_run(toy_classification,
                                                 artifact_dir):
    reg = MetricsRegistry()
    trainer = dk.DOWNPOUR(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        num_workers=2, batch_size=16, num_epoch=1,
        communication_window=4, registry=reg,
    )
    trainer.train(toy_classification)
    health = trainer.training_health
    assert health is not None
    sz = health.statusz()
    # Failure artifact: a red async-trainer run ships its worker table.
    (artifact_dir / "training_statusz.json").write_text(json.dumps(sz))

    assert sz["protocol"] == "downpour" and sz["num_workers"] == 2
    workers = {w["worker"] for w in sz["workers"]}
    assert workers == {0, 1}
    for w in sz["workers"]:
        assert w["commits"] >= 1 and w["pulls"] == 1
        assert "staleness_p50" in w and "staleness_p99" in w
    assert sz["staleness"]["samples"] >= 2
    assert sz["ps"]["num_commits"] == sum(
        w["commits"] for w in sz["workers"])
    assert sz["goodput"]["ratio"] == pytest.approx(1.0)  # DOWNPOUR: undamped
    # Overlapped exchanges rebase (default overlap_window=True).
    assert sum(w["rebases"] for w in sz["workers"]) >= 1
    # Memory rows exist and are typed (CPU backend may be blind — then
    # available=False with None bytes, never 0).
    assert sz["memory"], "no device memory rows"
    for m in sz["memory"]:
        if not m["available"]:
            assert m["bytes_in_use"] is None
    # Registry surface: the same story is scrapeable.
    snap = reg.snapshot()
    assert snap["train_commit_staleness"]["count"] >= 2
    assert snap["train_worker_pulls_total"]["value"] == 2
    # Human rendering: the statusz page names the load-bearing parts.
    page = format_statusz(sz)
    assert "workers:" in page and "staleness:" in page
    assert "goodput" in page and "device memory:" in page


def test_aeasgd_statusz_reports_divergence_on_real_run(toy_classification):
    trainer = dk.AEASGD(
        _model(), worker_optimizer="adam", learning_rate=0.05,
        num_workers=2, batch_size=16, num_epoch=1,
        communication_window=4, rho=2.0,
    )
    trainer.train(toy_classification)
    sz = trainer.training_health.statusz()
    assert sz["divergence"] is not None and sz["divergence"] > 0
    assert all("staleness_p99" in w for w in sz["workers"])
    assert "divergence" in format_statusz(sz)


def test_track_health_false_disables_the_layer(toy_classification):
    trainer = dk.DOWNPOUR(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        num_workers=1, batch_size=32, num_epoch=1,
        communication_window=8, track_health=False,
    )
    trainer.train(toy_classification)
    assert trainer.training_health is None


# -- shims & rendering --------------------------------------------------------

def test_tracing_trace_shim_forwards_to_promoted_helper():
    import distkeras_tpu.tracing as tracing
    from distkeras_tpu.telemetry.device import profile_trace

    with pytest.warns(DeprecationWarning, match="profile_trace"):
        shim = tracing.trace
    assert shim is profile_trace


def test_format_statusz_renders_canned_payload():
    payload = {
        "protocol": "dynsgd", "num_workers": 2, "uptime_s": 1.5,
        "staleness": {"p50": 1.0, "p90": 2.0, "p99": 3.0, "max": 3.0,
                      "samples": 7},
        "goodput": {"update_mass": 10.0, "applied_mass": 6.0,
                    "ratio": 0.6},
        "workers": [
            {"worker": 0, "commits": 4, "duplicates": 0, "pulls": 1,
             "rebases": 2, "last_commit_age_s": 0.1, "last_staleness": 1,
             "staleness_p50": 1.0, "staleness_p99": 2.0,
             "commit_rate_per_s": 3.0},
        ],
        "ps": {"running": True, "num_updates": 7, "num_commits": 7,
               "num_duplicates": 0, "queue_depth": 0,
               "snapshot_failures": 0},
        "memory": [
            {"device": "cpu:0", "available": False, "bytes_in_use": None},
            {"device": "tpu:0", "available": True,
             "bytes_in_use": 2**20, "bytes_limit": 4 * 2**20,
             "peak_bytes_in_use": 2 * 2**20, "headroom_bytes": 3 * 2**20},
        ],
    }
    page = format_statusz(payload)
    assert "protocol=dynsgd" in page
    assert "p99=3.0" in page
    assert "unavailable" in page          # the sentinel, not a fake 0
    assert "3.0" in page and "cpu:0" in page and "tpu:0" in page
    assert "queue_depth=0" in page
