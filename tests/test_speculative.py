"""Speculative decoding (draft/verify) in the serving engine.

The invariants under test, all on CPU with a tiny causal LM drafting
for itself (the sanity config — acceptance ~100%, so deep accept
prefixes and the remaining-budget clamp are exercised) and for a
*different* draft (low acceptance — rejection, zero-accept fallback
ticks, and rollback dominate):

- greedy streams are token-identical to one-shot ``generate()`` AND to
  a non-speculating engine, across plain, mixed-temperature, and
  shared-prefix batches, dense and paged, including requests that use
  the whole trained context (verify-window overhang);
- the armed ``RecompileAuditor`` stays silent: draft, verify, and the
  one-token fallback decode each compile exactly once, no matter how
  acceptance lengths vary;
- preemption-and-requeue mid-speculation resumes token-identically
  (accepted-and-streamed tokens fold into the resume prefill), and a
  pool too dry for lookahead blocks degrades throughput, never output;
- rolling weight reload under speculation swaps the TARGET only and
  post-swap output matches the new weights;
- accept accounting: ``spec_draft_tokens_total`` /
  ``spec_accepted_tokens_total`` counters, the accept-len histogram,
  summary keys, and the debugz accept-rate column.

Engines are deliberately few and shared within tests — every
ServingEngine construction compiles its program set (plus the ctor
warmup of the spec trio), which is what dominates this file's runtime.
"""

import asyncio

import numpy as np
import pytest

from distkeras_tpu.inference.generate import generate
from distkeras_tpu.models.bert import gpt_tiny
from distkeras_tpu.serving import ServingEngine
from distkeras_tpu.telemetry import RecompileAuditor

VOCAB = 64

SPEC_CALLABLES = ("serving_decode", "serving_draft", "serving_verify")


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(0)


@pytest.fixture(scope="module")
def other_lm():
    """A draft with different weights than the target: most proposals
    get rejected, so the zero-accept fallback path dominates."""
    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(11)


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


def _want(lm, prompt, n):
    model, variables = lm
    return generate(model, variables, np.asarray([prompt], np.int32), n,
                    greedy=True)[0].tolist()


def _spec_engine(lm, draft_lm=None, *, auditor=None, spec_k=4, **kw):
    model, variables = lm
    dm, dv = draft_lm if draft_lm is not None else lm
    return ServingEngine(
        model, variables, draft_model=dm, draft_variables=dv,
        spec_k=spec_k, auditor=auditor,
        arm_auditor_after_warmup=auditor is not None, **kw)


async def _run_engine(engine, coro):
    task = asyncio.create_task(engine.run())
    try:
        return await coro
    finally:
        engine.shutdown(drain=True)
        await task


def _drive_staggered(engine, jobs, **submit_kw):
    """``jobs``: (prompt, max_new_tokens) pairs, submitted staggered so
    later ones admit into freed slots mid-decode."""
    async def work():
        reqs = []
        for i, (p, n) in enumerate(jobs):
            reqs.append(engine.submit(p, n, **submit_kw))
            await asyncio.sleep(0.01 * i)
        return [await r.result() for r in reqs]

    return asyncio.run(_run_engine(engine, work()))


def _assert_compile_once(auditor):
    for name in SPEC_CALLABLES:
        assert auditor.compiles(name) == 1, name


# -- parity -------------------------------------------------------------------

def test_spec_greedy_parity_vs_generate_and_plain_engine(lm, rng):
    """Sanity config (draft==target): token-identical to generate() AND
    to a non-speculating engine, through staggered admissions into
    freed slots, INCLUDING a request that uses the whole trained
    context (20 + 12 == 32: the verify window overhangs the request
    limit on its final ticks) — with the auditor armed after the first
    tick."""
    model, variables = lm
    auditor = RecompileAuditor()
    engine = _spec_engine(lm, auditor=auditor, slots=2, max_queue=8)
    plain = ServingEngine(model, variables, slots=2, max_queue=8)
    jobs = [(_prompt(rng, n), 6) for n in (5, 9, 3, 7)]
    jobs.append((_prompt(rng, 20), 12))  # context-limit edge

    outs = _drive_staggered(engine, jobs)
    plain_outs = _drive_staggered(plain, jobs)
    for (p, n), got, plain_got in zip(jobs, outs, plain_outs):
        want = _want(lm, p, n)
        assert got == want  # vs offline generate()
        assert plain_got == want  # and vs the non-speculating engine
    _assert_compile_once(auditor)
    assert auditor.report()["serving_verify"]["armed"]
    assert engine.decode_compile_count() in (1, -1)
    s = engine.metrics.summary()
    # Draft == target: every usable draft accepted.
    assert s["spec_draft_tokens"] > 0
    assert s["spec_accept_rate"] == 1.0


def test_spec_low_acceptance_draft_still_parity_exact(lm, other_lm, rng):
    """A draft with unrelated weights: most proposals are rejected, so
    output flows through rollbacks and interleaved fallback ticks — and
    must STILL be token-identical to generate()."""
    auditor = RecompileAuditor()
    engine = _spec_engine(lm, other_lm, auditor=auditor, slots=2,
                          max_queue=8)
    jobs = [(_prompt(rng, n), 6) for n in (5, 9, 3, 7)]
    outs = _drive_staggered(engine, jobs)
    for (p, n), got in zip(jobs, outs):
        assert got == _want(lm, p, n)
    _assert_compile_once(auditor)
    s = engine.metrics.summary()
    # Rejection must actually have happened for this test to cover the
    # rollback + fallback paths.
    assert s["spec_accept_rate"] < 1.0


def test_spec_mixed_temperature_and_opt_out_one_batch(lm, rng):
    """Greedy rows speculate while temperature>0 rows (and an explicit
    speculate=False greedy row) ride the SAME batch — greedy output
    stays parity-exact, sampled output stays valid, and the opt-out
    greedy row is served by interleaved fallback ticks (strict parity),
    never booking draft statistics."""
    engine = _spec_engine(lm, slots=3, max_queue=8, seed=3)
    p = _prompt(rng, 5)
    p2 = _prompt(rng, 6)

    async def work():
        greedy = engine.submit(p, 8)
        hot = engine.submit(p, 8, temperature=5.0)
        optout = engine.submit(p2, 8, speculate=False)
        return (await greedy.result(), await hot.result(),
                await optout.result())

    g, h, o = asyncio.run(_run_engine(engine, work()))
    assert g == _want(lm, p, 8)
    assert o == _want(lm, p2, 8)  # opt-out: still greedy-exact
    assert all(0 <= t < VOCAB for t in h)
    # Only the speculating greedy row booked drafts — nothing from the
    # hot or opt-out rows — and in the sanity config it accepted all of
    # the (remaining-clamped) drafts it could use.
    dz = engine.debugz()
    assert dz["speculative"]["spec_k"] == 4
    assert engine.metrics.spec_draft_tokens > 0
    assert (engine.metrics.spec_accepted_tokens
            == engine.metrics.spec_draft_tokens)


def test_spec_shared_prefix_chunked_parity(lm, rng):
    """Speculation composes with chunked prefill + the prefix cache:
    shared-prefix batches stay parity-exact and still hit."""
    engine = _spec_engine(lm, slots=2, max_queue=16, prefill_chunk=4,
                          prefix_cache_mb=1.0, prefix_block_tokens=4)
    shared = _prompt(rng, 12)
    prompts = [shared + _prompt(rng, k) for k in (3, 4, 5, 3)]

    async def drive():
        outs = []
        for p in prompts:  # sequential: later prompts hit earlier ones
            outs.append(await engine.submit(p, 5).result())
        return outs

    outs = asyncio.run(_run_engine(engine, drive()))
    assert outs == [_want(lm, p, 5) for p in prompts]
    assert engine.prefix_cache.stats()["hit_requests"] >= 3
    assert engine.metrics.summary()["spec_accept_rate"] == 1.0


# -- paged: lookahead, preemption, resume ------------------------------------

def test_spec_paged_preempt_resume_and_room_clamp_parity(lm, rng):
    """ONE undersized pool covers the whole paged story: preemption
    fires while streams are mid-speculation (accepted-and-streamed
    tokens fold into the resume prefill), lookahead block allocs fail
    under pressure (the room clamp degrades tokens/tick, never
    correctness), a request uses the full trained context, and every
    stream still finishes token-identical with the armed auditor
    silent."""
    auditor = RecompileAuditor()
    engine = _spec_engine(lm, auditor=auditor, slots=2, max_queue=8,
                          kv_pool_blocks=8, kv_block_tokens=4)
    jobs = [(_prompt(rng, 9), 10), (_prompt(rng, 8), 10)]
    outs = _drive_staggered(engine, jobs)
    for (p, n), got in zip(jobs, outs):
        assert got == _want(lm, p, n)
    assert engine.metrics.preemptions >= 1  # pressure actually happened
    _assert_compile_once(auditor)
    # Full-context request on the same (reopened) engine: 20 + 12 == 32
    # fills the whole pool — 8 blocks at completion == capacity.
    engine.reopen()
    p = _prompt(rng, 20)
    out = _drive_staggered(engine, [(p, 12)])[0]
    assert out == _want(lm, p, 12)
    _assert_compile_once(auditor)
    # Draft == target, so every VERIFIED draft was accepted — but the
    # room clamp under pool pressure commits fewer than proposed on
    # some ticks (the designed degradation), so the rate sits just
    # below 1.0 rather than at it.
    rate = engine.metrics.summary()["spec_accept_rate"]
    assert 0.8 < rate <= 1.0, rate


# -- reload / swap ------------------------------------------------------------

def test_spec_rolling_reload_swaps_target_only(lm, rng):
    """request_param_swap under speculation: output before the swap
    matches the old weights, after matches the new — with the SAME
    draft (stale relative to the new target), which may cost accept
    rate but never correctness. The armed auditor proves the swap and
    the post-swap spec ticks never retraced."""
    model, variables = lm
    new_vars = model.init(7)
    auditor = RecompileAuditor()
    engine = _spec_engine(lm, auditor=auditor, slots=2, max_queue=8)
    p = _prompt(rng, 5)

    async def work():
        before = await engine.submit(p, 6).result()
        ev, res = engine.request_param_swap(new_vars)
        await ev.wait()
        assert res.get("ok"), res
        after = await engine.submit(p, 6).result()
        return before, after

    before, after = asyncio.run(_run_engine(engine, work()))
    assert before == _want(lm, p, 6)
    want_new = generate(model, new_vars, np.asarray([p], np.int32), 6,
                        greedy=True)[0].tolist()
    assert after == want_new
    _assert_compile_once(auditor)


# -- observability ------------------------------------------------------------

def test_spec_metrics_histogram_and_debugz_render(lm, rng):
    """Registry counters/histogram, summary keys, the debugz
    speculative section + per-slot accept column, and its text
    rendering — one engine serves all of it."""
    from distkeras_tpu.serving.debugz import format_debugz

    engine = _spec_engine(lm, slots=1, max_queue=4)
    p = _prompt(rng, 5)
    new_tokens = 24  # long enough that ticks remain after the first

    async def work():
        req = engine.submit(p, new_tokens)
        # Snapshot the debugz page mid-stream, once the slot has booked
        # draft statistics (the accept column needs a live slot); bail
        # to the done-check rather than spinning if it finishes first.
        page = None
        while not req.done.is_set():
            st = engine._slot_state[0]
            if st is not None and st.spec_drafted:
                page = format_debugz(engine.debugz())
                break
            await asyncio.sleep(0)
        out = await req.result()
        return page, out

    page, out = asyncio.run(_run_engine(engine, work()))
    assert out == _want(lm, p, new_tokens)
    assert page is not None, "request finished before a spec tick ran"
    assert "speculative: draft=gpt_tiny k=4" in page
    assert "accept" in page  # the slot-table column rendered
    snap = engine.metrics.registry.snapshot()
    drafted = snap["spec_draft_tokens_total"]["value"]
    accepted = snap["spec_accepted_tokens_total"]["value"]
    assert drafted > 0 and accepted == drafted  # sanity config
    hist = snap["serving_spec_accept_len"]
    assert hist["count"] >= 1  # one observation per speculating tick
    assert hist["sum"] == accepted
    s = engine.metrics.summary()
    assert s["spec_accept_rate"] == 1.0
    dz = engine.debugz()
    assert dz["speculative"]["accept_rate"] == 1.0
    assert dz["speculative"]["draft_model"] == "gpt_tiny"


def test_accept_length_reference_semantics():
    """The exported accept-rule helpers: prefix acceptance stops at the
    first rejection (accept_prefix_length), and the strict
    token-equality form (greedy_accept_length) — the reference
    semantics the engine's ε-relaxed gate is measured against."""
    import jax.numpy as jnp

    from distkeras_tpu.inference.generate import (
        accept_prefix_length,
        greedy_accept_length,
    )

    drafts = jnp.array([[1, 2, 3], [1, 9, 3], [9, 9, 9]], jnp.int32)
    target = jnp.array([[1, 2, 3], [1, 2, 3], [1, 2, 3]], jnp.int32)
    assert greedy_accept_length(drafts, target).tolist() == [3, 1, 0]
    # A later re-match after a mismatch must NOT count (d_{j+1} was
    # conditioned on the rejected d_j).
    assert accept_prefix_length(
        jnp.array([[True, False, True]])).tolist() == [1]


def test_spec_ctor_validation(lm):
    model, variables = lm
    with pytest.raises(ValueError, match="draft_variables"):
        ServingEngine(model, variables, draft_model=model)
    with pytest.raises(ValueError, match="spec_k"):
        ServingEngine(model, variables, draft_model=model,
                      draft_variables=variables, spec_k=0)
    other_vocab = gpt_tiny(seq_len=32, vocab_size=VOCAB * 2)
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(model, variables, draft_model=other_vocab,
                      draft_variables=other_vocab.init(0))
