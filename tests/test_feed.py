"""Minibatch feed + device prefetch tests."""

import jax
import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.data.feed import DeviceFeed, minibatches
from distkeras_tpu.parallel.mesh import best_mesh, data_parallel_shardings


def _ds(n=64, d=4):
    return Dataset.from_arrays(
        features=np.arange(n * d, dtype=np.float32).reshape(n, d),
        label=np.arange(n, dtype=np.float32),
    )


def test_minibatches_shapes_and_coverage():
    batches = list(minibatches(_ds(), 16))
    assert len(batches) == 4
    assert all(b["features"].shape == (16, 4) for b in batches)
    got = np.concatenate([b["label"] for b in batches])
    np.testing.assert_array_equal(got, np.arange(64))


def test_minibatches_drop_remainder():
    batches = list(minibatches(_ds(70), 16))
    assert len(batches) == 4  # 70 // 16


def test_minibatches_epochs_reshuffle():
    b1 = list(minibatches(_ds(), 16, num_epoch=2, seed=3))
    assert len(b1) == 8
    # different epoch order, same coverage per epoch
    e1 = np.sort(np.concatenate([b["label"] for b in b1[:4]]))
    e2 = np.sort(np.concatenate([b["label"] for b in b1[4:]]))
    np.testing.assert_array_equal(e1, e2)
    assert not np.array_equal(
        np.concatenate([b["label"] for b in b1[:4]]),
        np.concatenate([b["label"] for b in b1[4:]]),
    )


def test_device_feed_yields_all_batches_in_order():
    feed = DeviceFeed(minibatches(_ds(), 16), buffer_size=2)
    out = [np.asarray(b["label"]) for b in feed]
    assert len(out) == 4
    np.testing.assert_array_equal(np.concatenate(out), np.arange(64))


def test_device_feed_sharded_placement():
    mesh = best_mesh()
    batch_sh, _ = data_parallel_shardings(mesh)
    feed = DeviceFeed(minibatches(_ds(), 32), sharding=batch_sh)
    batch = next(iter(feed))
    assert {s.data.shape for s in batch["features"].addressable_shards} == {(4, 4)}


def test_device_feed_put_fn():
    calls = []

    def put(batch):
        calls.append(1)
        return batch

    feed = DeviceFeed(minibatches(_ds(), 16), put_fn=put)
    out = list(feed)
    assert len(out) == 4 and len(calls) == 4


def test_minibatches_start_batch_matches_islice():
    """Arithmetic resume fast-forward: start_batch=k yields exactly the
    stream islice(full, k, None) — across epoch boundaries, with shuffle."""
    import itertools

    full = list(minibatches(_ds(40), 16, num_epoch=3, seed=5))
    for k in (0, 1, 2, 3, 5, len(full)):
        skipped = list(
            minibatches(_ds(40), 16, num_epoch=3, seed=5, start_batch=k)
        )
        want = list(itertools.islice(iter(full), k, None))
        assert len(skipped) == len(want)
        for a, b in zip(skipped, want):
            np.testing.assert_array_equal(a["features"], b["features"])
            np.testing.assert_array_equal(a["label"], b["label"])
