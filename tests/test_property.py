"""Property-based tests (hypothesis): serializer round-trips and protocol
invariants over arbitrary inputs — the systematic version of SURVEY §4's
"property test hammering concurrent commits"."""

import numpy as np
import pytest

# Optional test extra: environments without hypothesis (it is in
# [test] but not a runtime dependency) get a clean module skip instead
# of a collection ERROR polluting the tier-1 report.
pytest.importorskip(
    "hypothesis",
    reason="hypothesis not installed (pip install distkeras-tpu[test])")
from hypothesis import given, settings, strategies as st  # noqa: E402

from distkeras_tpu.parallel.protocols import ADAGProtocol, DOWNPOURProtocol, DynSGDProtocol
from distkeras_tpu.utils.pytree import deserialize_pytree, serialize_pytree

# -- strategies --------------------------------------------------------------

leaf_shapes = st.lists(st.integers(1, 5), min_size=0, max_size=3).map(tuple)


@st.composite
def pytrees(draw, depth=2):
    if depth == 0 or draw(st.booleans()):
        shape = draw(leaf_shapes)
        return np.asarray(
            draw(
                st.lists(
                    st.floats(-1e6, 1e6, width=32),
                    min_size=int(np.prod(shape, dtype=int)),
                    max_size=int(np.prod(shape, dtype=int)),
                )
            ),
            np.float32,
        ).reshape(shape)
    n = draw(st.integers(1, 3))
    keys = draw(
        st.lists(
            st.text("abcdefgh_0123", min_size=1, max_size=6),
            min_size=n, max_size=n, unique=True,
        )
    )
    return {k: draw(pytrees(depth=depth - 1)) for k in keys}


@settings(max_examples=40, deadline=None)
@given(pytrees())
def test_serializer_roundtrip_arbitrary_trees(tree):
    back = deserialize_pytree(serialize_pytree(tree))

    def check(a, b):
        if isinstance(a, dict):
            assert set(a) == set(b)
            for k in a:
                check(a[k], b[k])
        else:
            np.testing.assert_array_equal(a, np.asarray(b))

    check(tree, back)


@settings(max_examples=30, deadline=None)
@given(
    st.lists(st.floats(-100, 100, width=32), min_size=1, max_size=30),
    st.integers(1, 8),
)
def test_downpour_center_is_sum_of_deltas(deltas, num_workers):
    """Additive protocol: any commit order yields center == Σ deltas."""
    p = DOWNPOURProtocol()
    center, n = {"w": np.zeros(1, np.float32)}, 0
    for d in deltas:
        center, n = p.server_commit(
            center, n, {"delta": {"w": np.full(1, d, np.float32)}}, num_workers
        )
    assert n == len(deltas)
    np.testing.assert_allclose(center["w"][0], np.float32(sum(np.float32(d) for d in deltas)), rtol=1e-4, atol=1e-4)


@settings(max_examples=30, deadline=None)
@given(
    st.lists(
        st.tuples(st.floats(-10, 10, width=32), st.integers(0, 1000)),
        min_size=1, max_size=30,
    )
)
def test_dynsgd_center_bounded_and_counter_exact(commits):
    """DynSGD: counter == #commits; each applied delta is damped (≤ |delta|)."""
    p = DynSGDProtocol()
    center, n = {"w": np.zeros(1, np.float64)}, 0
    bound = 0.0
    for d, last in commits:
        last = min(last, n)  # a worker can't have seen the future
        center, n = p.server_commit(
            center, n, {"delta": {"w": np.full(1, d)}, "last_update": last}, 2
        )
        bound += abs(d)
    assert n == len(commits)
    assert abs(center["w"][0]) <= bound * (1 + 1e-5) + 1e-6  # f32 accumulation slack


@settings(max_examples=20, deadline=None)
@given(st.integers(1, 16), st.floats(0.1, 10.0))
def test_adag_scaling_is_1_over_n(num_workers, mag):
    p = ADAGProtocol()
    center, n = p.server_commit(
        {"w": np.zeros(1, np.float64)}, 0,
        {"delta": {"w": np.full(1, mag)}}, num_workers,
    )
    np.testing.assert_allclose(center["w"][0], mag / num_workers)


@settings(max_examples=50, deadline=None)
@given(st.binary(min_size=0, max_size=64))
def test_grpc_frame_decoder_rejects_garbage(blob):
    """Arbitrary bytes must raise a clean error, never crash or hang."""
    from distkeras_tpu.parallel.ps_grpc import _decode_commit, _decode_pull_reply

    for decoder in (_decode_commit, _decode_pull_reply):
        try:
            decoder(blob)
        except Exception as e:
            assert not isinstance(e, (SystemExit, KeyboardInterrupt, MemoryError))


@settings(max_examples=30, deadline=None)
@given(
    st.lists(
        st.tuples(st.floats(-50, 50, width=32), st.integers(0, 4), st.booleans()),
        min_size=1, max_size=25,
    )
)
def test_fused_exchange_dedupe_exactly_once(events):
    """A stream of fused commit_pull exchanges with injected replays: each
    unique commit applies exactly once, every exchange (fresh or replayed)
    still gets a reply, and the final center equals the sum of unique
    deltas (DOWNPOUR)."""
    from distkeras_tpu.parallel.ps import ParameterServerService

    p = DOWNPOURProtocol()
    svc = ParameterServerService(p, {"w": np.zeros(1, np.float32)}, 1)
    svc.start()
    try:
        client = svc.client()
        expected = 0.0
        seen = set()
        for d, worker, replay in events:
            cid = f"w{worker}:{len(seen) if not replay else 0}"
            payload = {
                "delta": {"w": np.full(1, d, np.float32)},
                "last_update": 0,
                "commit_id": cid,
            }
            center, _ = client.commit_pull(payload)
            assert np.isfinite(center["w"]).all()
            if cid not in seen:
                seen.add(cid)
                expected += np.float32(d)
        final = svc.get_model()
        np.testing.assert_allclose(final["w"][0], expected, rtol=1e-3, atol=1e-3)
        assert svc.num_commits == len(seen)
    finally:
        svc.stop()
