"""Causal decoder LM tests."""

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models.bert import gpt_tiny


def test_causality(rng):
    """Changing future tokens must not change past logits."""
    model = gpt_tiny(seq_len=16, vocab_size=64)
    v = model.init(0)
    t1 = np.asarray(rng.integers(0, 64, size=(1, 16)), np.int32)
    t2 = t1.copy()
    t2[0, 10:] = (t2[0, 10:] + 7) % 64  # perturb the future
    o1, _ = model.apply(v, t1)
    o2, _ = model.apply(v, t2)
    np.testing.assert_allclose(
        np.asarray(o1)[0, :10], np.asarray(o2)[0, :10], atol=1e-4
    )
    assert not np.allclose(np.asarray(o1)[0, 10:], np.asarray(o2)[0, 10:])


@pytest.mark.slow
def test_next_token_training_learns(rng):
    """Train on a deterministic cyclic sequence; loss collapses."""
    seq, vocab = 16, 32
    base = np.arange(10_000) % vocab
    windows = np.stack([base[i : i + seq] for i in range(0, 512)])
    features = windows.astype(np.int32)
    labels = np.roll(windows, -1, axis=1).astype(np.int32)  # next token
    ds = dk.Dataset.from_arrays(features=features, label=labels)
    trainer = dk.SingleTrainer(
        gpt_tiny(seq_len=seq, vocab_size=vocab),
        worker_optimizer="adam", learning_rate=3e-3,
        loss="categorical_crossentropy", batch_size=64, num_epoch=4,
    )
    trainer.train(ds)
    hist = trainer.get_history()
    assert hist[-1]["loss"] < 0.5 * hist[0]["loss"], (
        hist[0]["loss"], hist[-1]["loss"]
    )
    # evaluate() convenience agrees with training-history scale
    trained = trainer.train(ds)
    m = trainer.evaluate(trained, ds, batch_size=128)
    assert m["accuracy"] > 0.9
