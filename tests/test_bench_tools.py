"""The bench drift gate and BASELINE append tooling (round 5): pure-python
helpers that decide what BENCH_r05's vs_baseline compares against — the
one guard on the only surface measurable every round."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (stdlib-only parent module)


def _write_round(tmp_path, n, metric, value, batch, device="TFRT_CPU_0",
                 shape="", forced=False, infra=False):
    detail = {"batch_size": batch, "device": device}
    if shape:
        detail["shape"] = shape
    if forced:
        detail["forced_cpu"] = True
    if infra:
        detail["infrastructure_failure"] = True
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps({
        "parsed": {"metric": metric, "value": value, "detail": detail}
    }))


def test_previous_same_config_prefers_latest_round(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    m = "mnist_mlp_train_samples_per_sec_per_chip"
    _write_round(tmp_path, 3, m, 100.0, 256)
    _write_round(tmp_path, 4, m, 200.0, 256)
    value, source = bench._previous_same_config(m, 256, True)
    assert value == 200.0 and source == "BENCH_r04.json"


def test_previous_same_config_filters_identity(tmp_path, monkeypatch):
    """batch, device kind, shape, forced flag, and infra rows all gate."""
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    m = "bert_base_mlm_train_samples_per_sec_per_chip"
    _write_round(tmp_path, 1, m, 1.0, 2, shape="seq64", forced=True)
    _write_round(tmp_path, 2, m, 9.0, 2, shape="seq128", forced=True)
    _write_round(tmp_path, 3, m, 5.0, 2, shape="seq64", forced=True, infra=True)
    # same shape+forced -> r01 (r02 is a different shape, r03 is infra)
    value, source = bench._previous_same_config(m, 2, True, "seq64", True)
    assert (value, source) == (1.0, "BENCH_r01.json")
    # organic lookup never sees forced rows
    assert bench._previous_same_config(m, 2, True, "seq64", False) == (None, None)
    # batch mismatch
    assert bench._previous_same_config(m, 4, True, "seq64", True) == (None, None)
    # a TPU lookup never matches CPU rows
    assert bench._previous_same_config(m, 2, False, "seq64", True) == (None, None)


def test_shapeless_prior_matches_only_empty_shape(tmp_path, monkeypatch):
    """Rows recorded before the shape field existed (BENCH_r04's mlp row)
    compare as shape \"\" — matching mlp, never bert/resnet defaults."""
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    m = "mnist_mlp_train_samples_per_sec_per_chip"
    _write_round(tmp_path, 4, m, 34026.13, 256)  # no shape, no forced
    assert bench._previous_same_config(m, 256, True) == (
        34026.13, "BENCH_r04.json"
    )
    assert bench._previous_same_config(m, 256, True, "seq128") == (None, None)


def test_record_history_roundtrip_and_fallback(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    m = "resnet50_train_samples_per_sec_per_chip"
    bench._record_history(m, 4, True, 5.29, "img64", True)
    # no BENCH_r rows -> the history file answers
    value, source = bench._previous_same_config(m, 4, True, "img64", True)
    assert (value, source) == (5.29, "bench_history.json")
    # overwrite is atomic and keyed
    bench._record_history(m, 4, True, 6.0, "img64", True)
    hist = json.loads((tmp_path / "bench_history.json").read_text())
    key = bench._config_key(m, 4, True, "img64", True)
    assert hist[key]["value"] == 6.0 and len(hist) == 1
    # corrupt file degrades to no-prior instead of crashing
    (tmp_path / "bench_history.json").write_text("{truncated")
    assert bench._previous_same_config(m, 4, True, "img64", True) == (None, None)


def test_append_baseline_check_accepts_and_refuses(tmp_path):
    from scripts import append_baseline

    good = tmp_path / "good.json"
    good.write_text(json.dumps({"metric": "x", "value": 1.0,
                                "detail": {"device": "cpu"}}) + "\n")
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"metric": "x", "value": 0.0,
                               "detail": {"infrastructure_failure": True}}) + "\n")
    assert append_baseline.load_record(str(good))["value"] == 1.0
    rec = append_baseline.load_record(str(bad))
    assert rec["detail"]["infrastructure_failure"]


def test_record_history_keeps_prior_trail(tmp_path, monkeypatch):
    """Overwrites push the displaced entry onto a bounded prev trail —
    the raw material of the latest-vs-prior drift check."""
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    m = "mnist_mlp_train_samples_per_sec_per_chip"
    bench._record_history(m, 256, True, 100.0)
    bench._record_history(m, 256, True, 95.0)
    bench._record_history(m, 256, True, 90.0)
    hist = json.loads((tmp_path / "bench_history.json").read_text())
    entry = hist[bench._config_key(m, 256, True)]
    assert entry["value"] == 90.0
    assert [p["value"] for p in entry["prev"]] == [100.0, 95.0]
    # _previous_same_config still reads the flat value.
    assert bench._previous_same_config(m, 256, True)[0] == 90.0
    # A null row (aborted child) never enters or pollutes the trail.
    hist[bench._config_key(m, 256, True)]["value"] = None
    (tmp_path / "bench_history.json").write_text(json.dumps(hist))
    bench._record_history(m, 256, True, 85.0)
    entry = json.loads((tmp_path / "bench_history.json").read_text())[
        bench._config_key(m, 256, True)]
    assert entry["value"] == 85.0
    assert [p["value"] for p in entry["prev"]] == [100.0, 95.0]


def test_check_bench_regression_warns_and_strict_gates(tmp_path, capsys):
    from scripts import check_bench_regression as cbr

    path = tmp_path / "bench_history.json"
    path.write_text(json.dumps({
        "a/batch256/cpu": {"value": 80.0, "when": "2026-08-03T00:00:02Z",
                           "prev": [{"value": 100.0,
                                     "when": "2026-08-02T00:00:01Z"}]},
        "b/batch64/cpu": {"value": 99.0, "when": "2026-08-01T00:00:00Z",
                          "prev": [{"value": 100.0,
                                    "when": "2026-07-31T00:00:00Z"}]},
    }))
    # Default: latest-updated config only ('a'), 20% drop -> warn, exit 0.
    rc = cbr.main(["--history", str(path)])
    out = capsys.readouterr().out
    assert rc == 0 and "REGRESSION" in out and "a/batch256/cpu" in out
    assert "b/batch64" not in out
    # --all covers both; 'b' is within threshold.
    rc = cbr.main(["--history", str(path), "--all"])
    out = capsys.readouterr().out
    assert rc == 0 and "[ok] b/batch64/cpu" in out
    # --strict turns the warning into a gate.
    assert cbr.main(["--history", str(path), "--strict"]) == 1
    # A looser threshold passes strict.
    assert cbr.main(["--history", str(path), "--strict",
                     "--threshold", "0.5"]) == 0
    # Missing/corrupt history degrades to exit 0, never a crash.
    assert cbr.main(["--history", str(tmp_path / "nope.json")]) == 0
    path.write_text("{truncated")
    assert cbr.main(["--history", str(path)]) == 0


def test_check_bench_regression_serving_rows_are_direction_aware(
        tmp_path, capsys):
    """Serving latency rows regress by RISING; hit rate / goodput (and
    every training row) keep the lower-value-is-regression rule."""
    from scripts import check_bench_regression as cbr

    path = tmp_path / "bench_history.json"
    prev = [{"value": 0.010, "when": "2026-08-01T00:00:00Z"}]
    path.write_text(json.dumps({
        # TTFT doubled: that IS the regression even though value > prior.
        "serving/gpt_tiny/slots4/closed/ttft_p99_s":
            {"value": 0.020, "when": "2026-08-03T00:00:01Z", "prev": prev},
        # TTFT halved: an improvement, must NOT warn.
        "serving/gpt_tiny/slots4/open/ttft_p50_s":
            {"value": 0.005, "when": "2026-08-03T00:00:02Z", "prev": prev},
        # Hit rate dropped 40%: higher-is-better, warns.
        "serving/gpt_tiny/slots4/closed/prefix_hit_rate":
            {"value": 0.3, "when": "2026-08-03T00:00:03Z",
             "prev": [{"value": 0.5, "when": "2026-08-01T00:00:00Z"}]},
        # Training throughput row: unchanged semantics.
        "a/batch256/cpu":
            {"value": 100.0, "when": "2026-08-03T00:00:04Z",
             "prev": [{"value": 100.0, "when": "2026-08-01T00:00:00Z"}]},
    }))
    rc = cbr.main(["--history", str(path), "--all"])
    out = capsys.readouterr().out
    assert rc == 0  # warn-only
    assert "[REGRESSION] serving/gpt_tiny/slots4/closed/ttft_p99_s" in out
    assert "[ok] serving/gpt_tiny/slots4/open/ttft_p50_s" in out
    assert "[REGRESSION] serving/gpt_tiny/slots4/closed/prefix_hit_rate" \
        in out
    assert "[ok] a/batch256/cpu" in out
    assert cbr.main(["--history", str(path), "--all", "--strict"]) == 1
    # Direction helper: exact metric-name prefixes, not substrings.
    assert cbr.lower_is_better("serving/m/slots1/closed/inter_token_p99_s")
    assert cbr.lower_is_better("serving/m/slots1/open/queue_wait_p50_s")
    assert not cbr.lower_is_better("serving/m/slots1/open/goodput_tokens_per_sec")
    assert not cbr.lower_is_better("bert_train_samples_per_sec/batch8/cpu")
    # Training-health rows: commit staleness regresses UP, goodput DOWN.
    assert cbr.lower_is_better("train/dynsgd/workers4/staleness_p99")
    assert not cbr.lower_is_better("train/dynsgd/workers4/goodput_ratio")


def test_check_bench_regression_speculative_rows_direction(
        tmp_path, capsys):
    """serving/spec_* rows (serving_bench --speculate --record-history):
    accept rate and goodput regress by DROPPING, the ITL percentiles by
    RISING — the strict `--only serving/` CI gate must fire on an
    accept-rate collapse, not on an accept-rate improvement."""
    from scripts import check_bench_regression as cbr

    path = tmp_path / "bench_history.json"
    path.write_text(json.dumps({
        # Accept rate collapsed 0.9 -> 0.4: the draft stopped predicting
        # the target — a regression even though latency may look fine.
        "serving/spec_gpt_tiny/slots4/k4/closed/spec_accept_rate":
            {"value": 0.4, "when": "2026-08-04T00:00:01Z",
             "prev": [{"value": 0.9, "when": "2026-08-01T00:00:00Z"}]},
        # Speculative goodput doubled: an improvement, must NOT warn.
        "serving/spec_gpt_tiny/slots4/k4/closed/goodput_tokens_per_sec":
            {"value": 400.0, "when": "2026-08-04T00:00:02Z",
             "prev": [{"value": 200.0, "when": "2026-08-01T00:00:00Z"}]},
        # Speculative ITL doubled: latency-shaped, regresses UP.
        "serving/spec_gpt_tiny/slots4/k4/closed/inter_token_p99_s":
            {"value": 0.004, "when": "2026-08-04T00:00:03Z",
             "prev": [{"value": 0.002, "when": "2026-08-01T00:00:00Z"}]},
    }))
    rc = cbr.main(["--history", str(path), "--all", "--only", "serving/"])
    out = capsys.readouterr().out
    assert rc == 0  # warn-only without --strict
    assert ("[REGRESSION] serving/spec_gpt_tiny/slots4/k4/closed/"
            "spec_accept_rate") in out
    assert ("[ok] serving/spec_gpt_tiny/slots4/k4/closed/"
            "goodput_tokens_per_sec") in out
    assert ("[REGRESSION] serving/spec_gpt_tiny/slots4/k4/closed/"
            "inter_token_p99_s") in out
    # The strict serving gate (the CI lane) fails on the collapse.
    assert cbr.main(["--history", str(path), "--all", "--strict",
                     "--only", "serving/"]) == 1
    assert not cbr.lower_is_better(
        "serving/spec_gpt_tiny/slots4/k4/closed/spec_accept_rate")


def test_check_bench_regression_sharded_rows_direction(tmp_path, capsys):
    """serving/sharded_* rows (serving_bench --mesh --record-history)
    ride the strict serving/ gate with the standard directions: goodput
    regresses DOWN, latency percentiles UP."""
    import json as _json

    from scripts import check_bench_regression as cbr

    path = tmp_path / "bench_history.json"
    path.write_text(_json.dumps({
        "serving/sharded_gpt_tiny_tp2/slots4/closed/goodput_tokens_per_sec":
            {"value": 20.0, "when": "2026-08-04T00:00:01Z",
             "prev": [{"value": 40.0, "when": "2026-08-01T00:00:00Z"}]},
        "serving/sharded_gpt_tiny_tp2/slots4/closed/ttft_p50_s":
            {"value": 0.02, "when": "2026-08-04T00:00:02Z",
             "prev": [{"value": 0.04, "when": "2026-08-01T00:00:00Z"}]},
    }))
    rc = cbr.main(["--history", str(path), "--all", "--strict",
                   "--only", "serving/"])
    out = capsys.readouterr().out
    assert rc == 1  # the goodput halving fires the strict gate
    assert ("[REGRESSION] serving/sharded_gpt_tiny_tp2/slots4/closed/"
            "goodput_tokens_per_sec") in out
    # TTFT halved = improvement for a lower-is-better metric.
    assert ("[ok] serving/sharded_gpt_tiny_tp2/slots4/closed/"
            "ttft_p50_s") in out


def test_check_bench_regression_skips_unusable_rows(tmp_path):
    from scripts import check_bench_regression as cbr

    path = tmp_path / "bench_history.json"
    path.write_text(json.dumps({
        # No prior trail at all.
        "a/batch1/cpu": {"value": 1.0, "when": "2026-08-03T00:00:00Z"},
        # Null value (aborted child) and zero prior must both be skipped.
        "b/batch1/cpu": {"value": None, "when": "2026-08-03T00:00:01Z",
                         "prev": [{"value": 2.0, "when": "x"}]},
        "c/batch1/cpu": {"value": 5.0, "when": "2026-08-03T00:00:02Z",
                         "prev": [{"value": 0.0, "when": "x"}]},
    }))
    assert cbr.main(["--history", str(path), "--all"]) == 0


def test_ring_balance_combinatorics():
    """The analytic ring-balance bench conserves total causal work in both
    layouts and the striped makespan approaches the 2x asymptote."""
    from benchmarks.ring_balance import hop_work

    p, s_local = 8, 64
    S = p * s_local
    for layout in ("contiguous", "striped"):
        w = hop_work(p, s_local, layout)
        assert int(w.sum()) == S * (S + 1) // 2  # exact causal triangle
    contig = hop_work(p, s_local, "contiguous")
    striped = hop_work(p, s_local, "striped")
    ratio = contig.max(axis=0).sum() / striped.max(axis=0).sum()
    assert 1.7 < ratio < 2.0
    # striped per-hop spread is at most one diagonal (s_local units)
    assert int(striped.max() - striped.min()) == s_local
