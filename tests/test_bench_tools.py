"""The bench drift gate and BASELINE append tooling (round 5): pure-python
helpers that decide what BENCH_r05's vs_baseline compares against — the
one guard on the only surface measurable every round."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (stdlib-only parent module)


def _write_round(tmp_path, n, metric, value, batch, device="TFRT_CPU_0",
                 shape="", forced=False, infra=False):
    detail = {"batch_size": batch, "device": device}
    if shape:
        detail["shape"] = shape
    if forced:
        detail["forced_cpu"] = True
    if infra:
        detail["infrastructure_failure"] = True
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps({
        "parsed": {"metric": metric, "value": value, "detail": detail}
    }))


def test_previous_same_config_prefers_latest_round(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    m = "mnist_mlp_train_samples_per_sec_per_chip"
    _write_round(tmp_path, 3, m, 100.0, 256)
    _write_round(tmp_path, 4, m, 200.0, 256)
    value, source = bench._previous_same_config(m, 256, True)
    assert value == 200.0 and source == "BENCH_r04.json"


def test_previous_same_config_filters_identity(tmp_path, monkeypatch):
    """batch, device kind, shape, forced flag, and infra rows all gate."""
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    m = "bert_base_mlm_train_samples_per_sec_per_chip"
    _write_round(tmp_path, 1, m, 1.0, 2, shape="seq64", forced=True)
    _write_round(tmp_path, 2, m, 9.0, 2, shape="seq128", forced=True)
    _write_round(tmp_path, 3, m, 5.0, 2, shape="seq64", forced=True, infra=True)
    # same shape+forced -> r01 (r02 is a different shape, r03 is infra)
    value, source = bench._previous_same_config(m, 2, True, "seq64", True)
    assert (value, source) == (1.0, "BENCH_r01.json")
    # organic lookup never sees forced rows
    assert bench._previous_same_config(m, 2, True, "seq64", False) == (None, None)
    # batch mismatch
    assert bench._previous_same_config(m, 4, True, "seq64", True) == (None, None)
    # a TPU lookup never matches CPU rows
    assert bench._previous_same_config(m, 2, False, "seq64", True) == (None, None)


def test_shapeless_prior_matches_only_empty_shape(tmp_path, monkeypatch):
    """Rows recorded before the shape field existed (BENCH_r04's mlp row)
    compare as shape \"\" — matching mlp, never bert/resnet defaults."""
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    m = "mnist_mlp_train_samples_per_sec_per_chip"
    _write_round(tmp_path, 4, m, 34026.13, 256)  # no shape, no forced
    assert bench._previous_same_config(m, 256, True) == (
        34026.13, "BENCH_r04.json"
    )
    assert bench._previous_same_config(m, 256, True, "seq128") == (None, None)


def test_record_history_roundtrip_and_fallback(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    m = "resnet50_train_samples_per_sec_per_chip"
    bench._record_history(m, 4, True, 5.29, "img64", True)
    # no BENCH_r rows -> the history file answers
    value, source = bench._previous_same_config(m, 4, True, "img64", True)
    assert (value, source) == (5.29, "bench_history.json")
    # overwrite is atomic and keyed
    bench._record_history(m, 4, True, 6.0, "img64", True)
    hist = json.loads((tmp_path / "bench_history.json").read_text())
    key = bench._config_key(m, 4, True, "img64", True)
    assert hist[key]["value"] == 6.0 and len(hist) == 1
    # corrupt file degrades to no-prior instead of crashing
    (tmp_path / "bench_history.json").write_text("{truncated")
    assert bench._previous_same_config(m, 4, True, "img64", True) == (None, None)


def test_append_baseline_check_accepts_and_refuses(tmp_path):
    from scripts import append_baseline

    good = tmp_path / "good.json"
    good.write_text(json.dumps({"metric": "x", "value": 1.0,
                                "detail": {"device": "cpu"}}) + "\n")
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"metric": "x", "value": 0.0,
                               "detail": {"infrastructure_failure": True}}) + "\n")
    assert append_baseline.load_record(str(good))["value"] == 1.0
    rec = append_baseline.load_record(str(bad))
    assert rec["detail"]["infrastructure_failure"]


def test_ring_balance_combinatorics():
    """The analytic ring-balance bench conserves total causal work in both
    layouts and the striped makespan approaches the 2x asymptote."""
    from benchmarks.ring_balance import hop_work

    p, s_local = 8, 64
    S = p * s_local
    for layout in ("contiguous", "striped"):
        w = hop_work(p, s_local, layout)
        assert int(w.sum()) == S * (S + 1) // 2  # exact causal triangle
    contig = hop_work(p, s_local, "contiguous")
    striped = hop_work(p, s_local, "striped")
    ratio = contig.max(axis=0).sum() / striped.max(axis=0).sum()
    assert 1.7 < ratio < 2.0
    # striped per-hop spread is at most one diagonal (s_local units)
    assert int(striped.max() - striped.min()) == s_local
