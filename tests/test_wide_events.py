"""Wide-event request analytics: columnar ring, queryz, tail retention.

The observability tentpole under test, layer by layer:

- **store**: one flat ~40-column record per finished request in a
  columnar overwrite ring — typed null sentinels, interned strings,
  unknown columns rejected loudly, oldest-first overwrite;
- **query engine**: one-scan filter / group_by (<=2, cardinality
  capped into ``__other__``) / aggs (count - sum - mean - pX) whose
  results match an offline recompute, and whose pX payloads carry
  mergeable histogram states on the ONE shared bucket layout;
- **fleet merge**: ``merge_query_results`` over per-replica results
  equals ONE pooled store holding every event — counts and sums exact,
  percentiles bucket-exact — and the router's ``queryz`` fan-out over a
  jax-free Echo fleet reproduces that equality over real TCP (Echo
  latencies are a pure function of the prompt, so the expected fleet
  percentiles are recomputable offline);
- **tail retention**: an overwrite-pressure flood keeps 100% of error
  records and SLO-page-exemplar pins retrievable (the acceptance
  criterion), pin-before-arrival protects ids the router learns about
  before the replica finishes, and the router pins page-event
  exemplars fleet-wide;
- **engine**: every finished request emits exactly one wide event at
  done-time, the ``queryz`` verb answers over the wire, and the ARMED
  RecompileAuditor proves the analytics plane never touches the
  compiled decode step;
- **surfaces**: flight-recorder dumps embed the ring tail;
  ``format_queryz`` / ``run.py queryz`` render the fleet page.
"""

import asyncio
import bisect
import contextlib
import io
import json
import threading

import pytest

from distkeras_tpu.telemetry.request_trace import (
    TailRetention,
    TraceStore,
    new_trace_id,
)
from distkeras_tpu.telemetry.wide_events import (
    WIDE_HIST_BUCKETS,
    WideEventStore,
    merge_query_results,
    parse_aggs,
    parse_where,
)

SUP = dict(health_interval_s=0.05, health_timeout_s=2.0, fail_after=2,
           base_delay_s=0.05, max_delay_s=1.0, stable_after_s=0.5)


def _bucket_width_ok(value: float, truth: float) -> bool:
    """True when ``value`` is within one WIDE_HIST_BUCKETS bucket of
    ``truth`` — the documented fleet-percentile error bound."""
    i = bisect.bisect_left(WIDE_HIST_BUCKETS, truth)
    lo = WIDE_HIST_BUCKETS[max(0, i - 1)]
    hi = WIDE_HIST_BUCKETS[min(len(WIDE_HIST_BUCKETS) - 1, i + 1)]
    return lo <= value <= hi


def _percentile(values, q: float) -> float:
    """Nearest-rank percentile — the offline ground truth."""
    vals = sorted(values)
    idx = max(0, min(len(vals) - 1,
                     int(round(q / 100.0 * (len(vals) - 1)))))
    return vals[idx]


# -- columnar store -----------------------------------------------------------

def test_ring_overwrite_null_sentinels_and_unknown_column():
    store = WideEventStore(capacity=4)
    for i in range(6):
        store.append({"trace_id": f"t{i}", "tenant": f"ten{i}",
                      "prompt_tokens": i, "latency_s": 0.1 * (i + 1)})
    assert len(store) == 4
    st = store.stats()
    assert st["appended"] == 6 and st["rows"] == 4
    assert st["overwritten"] == 2
    assert st["append_ns_total"] > 0 and st["append_ns_mean"] > 0

    tail = store.tail(10)
    # Oldest two rows were overwritten; newest last.
    assert [r["trace_id"] for r in tail] == ["t2", "t3", "t4", "t5"]
    # Null sentinels: unset columns are OMITTED from the export, not
    # emitted as None/-1/NaN/"".
    row = tail[-1]
    assert row["prompt_tokens"] == 5
    assert "output_tokens" not in row and "ttft_s" not in row
    assert "kind" not in row

    with pytest.raises(ValueError, match="unknown wide-event col"):
        store.append({"trace_id": "x", "latency_ms": 5})
    with pytest.raises(ValueError, match="capacity"):
        WideEventStore(capacity=0)


def test_query_matches_offline_recompute():
    store = WideEventStore(capacity=256)
    rows = []
    for i in range(60):
        row = {"trace_id": f"t{i}",
               "tenant": "alpha" if i % 3 else "beta",
               "kind": "sample" if i % 2 else "score",
               "prompt_tokens": i,
               "ttft_s": 0.002 * (i + 1),
               "latency_s": 0.01 * (i + 1)}
        rows.append(row)
        store.append(row)

    out = store.query(where=["kind=sample", "prompt_tokens>=10"],
                      group_by=["tenant"],
                      aggs=["count", "sum:prompt_tokens",
                            "mean:latency_s", "p50:ttft_s"])
    want = [r for r in rows
            if r["kind"] == "sample" and r["prompt_tokens"] >= 10]
    assert out["matched"] == len(want) and out["scanned"] == 60
    assert out["aggs"] == ["count", "sum:prompt_tokens",
                           "mean:latency_s", "p50:ttft_s"]
    by_tenant = {g["key"]["tenant"]: g for g in out["groups"]}
    assert set(by_tenant) == {"alpha", "beta"}
    for tenant, g in by_tenant.items():
        sub = [r for r in want if r["tenant"] == tenant]
        assert g["count"] == len(sub)
        assert g["aggs"]["count"]["value"] == len(sub)
        assert g["aggs"]["sum:prompt_tokens"]["value"] == pytest.approx(
            sum(r["prompt_tokens"] for r in sub))
        assert g["aggs"]["mean:latency_s"]["value"] == pytest.approx(
            sum(r["latency_s"] for r in sub) / len(sub))
        p50 = g["aggs"]["p50:ttft_s"]
        truth = _percentile([r["ttft_s"] for r in sub], 50)
        assert _bucket_width_ok(p50["value"], truth), (p50["value"], truth)
        # The mergeable part rides along: a histogram state on the
        # shared layout, with the quantile it answers.
        assert p50["q"] == 50 and p50["state"]["count"] == len(sub)

    # No group_by: one ALL group; default agg is count.
    allq = store.query(where=["tenant=beta"])
    assert allq["groups"][0]["key"] == {}
    assert allq["groups"][0]["count"] == sum(
        1 for r in rows if r["tenant"] == "beta")


def test_query_cardinality_cap_folds_other():
    store = WideEventStore(capacity=256)
    for i in range(40):
        store.append({"trace_id": f"t{i}", "tenant": f"ten{i % 10}",
                      "latency_s": 0.1})
    out = store.query(group_by=["tenant"], aggs=["count"], max_groups=4)
    keys = [g["key"]["tenant"] for g in out["groups"]]
    assert "__other__" in keys
    assert len(keys) == 5  # 4 real + the fold bucket
    assert out["folded_groups"] == 6
    # Nothing dropped: counts are conserved across the fold.
    assert sum(g["count"] for g in out["groups"]) == 40
    assert out["matched"] == 40


def test_query_and_parse_typed_errors():
    store = WideEventStore(capacity=8)
    store.append({"trace_id": "t", "tenant": "a", "latency_s": 0.1})
    with pytest.raises(ValueError, match="capped at 2"):
        store.query(group_by=["tenant", "kind", "replica"])
    with pytest.raises(ValueError, match="unknown column"):
        store.query(group_by=["tennant"])
    with pytest.raises(ValueError, match="float column"):
        store.query(group_by=["latency_s"])
    with pytest.raises(ValueError, match="numeric column"):
        store.query(aggs=["p99:tenant"])
    with pytest.raises(ValueError, match="unknown aggregate"):
        store.query(aggs=["median:latency_s"])
    with pytest.raises(ValueError, match="percentile out of range"):
        parse_aggs(["p105:latency_s"])
    with pytest.raises(ValueError, match="malformed where"):
        parse_where(["tenant"])
    with pytest.raises(ValueError, match="unknown column"):
        parse_where(["nope=1"])
    with pytest.raises(ValueError, match="needs a numeric column"):
        parse_where(["tenant>5"])
    with pytest.raises(ValueError, match="numeric"):
        parse_where(["latency_s=fast"])
    with pytest.raises(ValueError, match="max_groups"):
        store.query(max_groups=0)


# -- fleet merge --------------------------------------------------------------

def _synthetic_rows(n, replica):
    return [{"trace_id": f"{replica}-{i}", "tenant": f"ten{i % 3}",
             "kind": "sample", "replica": replica,
             "prompt_tokens": 3 + i,
             "ttft_s": 0.001 * (i + 1) * (2 if replica == "r1" else 1),
             "latency_s": 0.005 * (i + 1)}
            for i in range(n)]


def test_merge_equals_pooled_single_store():
    """THE fleet invariant: merging per-replica query results equals one
    store holding every replica's events — counts/sums exact, pX
    payloads bucket-exact (identical, both live on WIDE_HIST_BUCKETS)."""
    spec = dict(where=["kind=sample"], group_by=["tenant"],
                aggs=["count", "sum:prompt_tokens", "mean:latency_s",
                      "p99:ttft_s"])
    pooled = WideEventStore(capacity=512)
    results = []
    for replica, n in (("r0", 17), ("r1", 29), ("r2", 5)):
        store = WideEventStore(capacity=64)
        for row in _synthetic_rows(n, replica):
            store.append(row)
            pooled.append(row)
        results.append(store.query(**spec))

    merged = merge_query_results(results)
    truth = pooled.query(**spec)
    assert merged["merged_from"] == 3
    assert merged["matched"] == truth["matched"] == 17 + 29 + 5
    t_groups = {g["key"]["tenant"]: g for g in truth["groups"]}
    m_groups = {g["key"]["tenant"]: g for g in merged["groups"]}
    assert set(m_groups) == set(t_groups)
    for tenant, tg in t_groups.items():
        mg = m_groups[tenant]
        assert mg["count"] == tg["count"]
        assert mg["aggs"]["count"]["value"] == tg["aggs"]["count"]["value"]
        assert mg["aggs"]["sum:prompt_tokens"]["value"] == pytest.approx(
            tg["aggs"]["sum:prompt_tokens"]["value"])
        assert mg["aggs"]["mean:latency_s"]["value"] == pytest.approx(
            tg["aggs"]["mean:latency_s"]["value"])
        # Bucket-exact: the merged histogram state IS the pooled state,
        # so the recomputed percentile is equal, not just close.
        assert mg["aggs"]["p99:ttft_s"]["value"] == pytest.approx(
            tg["aggs"]["p99:ttft_s"]["value"])
        assert (mg["aggs"]["p99:ttft_s"]["state"]["counts"]
                == tg["aggs"]["p99:ttft_s"]["state"]["counts"])
    # Merging never mutates the inputs (the router logs them too).
    assert results[0]["groups"][0]["count"] != merged["groups"][0]["count"]


def test_merge_shape_mismatch_and_empty_raise():
    store = WideEventStore(capacity=8)
    store.append({"trace_id": "t", "tenant": "a", "ttft_s": 0.1})
    a = store.query(group_by=["tenant"], aggs=["count"])
    b = store.query(group_by=["kind"], aggs=["count"])
    with pytest.raises(ValueError, match="different shape"):
        merge_query_results([a, b])
    with pytest.raises(ValueError, match="zero"):
        merge_query_results([])
    # None entries (unreachable replicas) are skipped, not fatal.
    m = merge_query_results([a, None, a])
    assert m["merged_from"] == 2 and m["matched"] == 2


# -- tail-based retention -----------------------------------------------------

def _finished(tid, status="ok", latency=0.01, tenant="bulk",
              kind="generate", slo=False):
    data = {"status": status, "latency_s": latency, "tenant": tenant,
            "kind": kind}
    if slo:
        data["slo_violation"] = True
    return {"trace_id": tid, "role": "engine", "source": "r0",
            "t_start": 0.0, "events": [], "data": data}


def test_flood_keeps_all_errors_and_pinned_exemplars():
    """The acceptance criterion: a tiny window under a 50x overwrite
    flood keeps EVERY error record and EVERY SLO-page-exemplar pin
    retrievable, while bulk-healthy traffic is (mostly) discarded."""
    store = TraceStore(capacity=16, retention=TailRetention(warmup=10),
                       keeper_capacity=64)
    errors = [new_trace_id() for _ in range(8)]
    exemplars = [new_trace_id() for _ in range(3)]
    for tid in exemplars:
        store.put(_finished(tid, slo=True))
        store.pin(tid)
    flood = 0
    for i in range(800):
        store.put(_finished(f"bulk{i}"))
        flood += 1
        if i % 100 == 50:
            store.put(_finished(errors[i // 100], status="error",
                                latency=0.5))
    # Window long gone: 800 healthy puts through a 16-slot ring.
    assert store.evicted > 700
    for tid in errors:
        hops = store.get_all(tid)
        assert hops, f"error trace {tid} lost under flood"
        assert hops[0]["data"]["status"] == "error"
    for tid in exemplars:
        assert store.get_all(tid), f"pinned exemplar {tid} lost"
    st = store.stats()
    assert st["pinned"] == 3
    assert st["keep_reasons"]["pinned"] == 3
    assert st["keep_reasons"]["error"] == 8
    # The keeper reservoir stayed bounded while doing it.
    assert st["keepers"] <= 64 + 3
    got = {r["trace_id"] for r in store.keepers(reason="error")}
    assert got == set(errors)


def test_pin_before_arrival_and_keeper_upgrade():
    store = TraceStore(capacity=4, retention=TailRetention(warmup=5),
                       keeper_capacity=8)
    # Pin-before-arrival: the router pins an exemplar id for a request
    # some replica is still serving.
    assert store.pin("feedbeef00000001")
    store.put(_finished("feedbeef00000001"))
    for i in range(20):
        store.put(_finished(f"x{i}"))
    hops = store.get_all("feedbeef00000001")
    assert hops and store.stats()["keep_reasons"]["pinned"] >= 1

    # Upgrade-in-place: a record already kept (as an error) becomes
    # pinned, and survives keeper eviction pressure afterwards.
    store2 = TraceStore(capacity=4, retention=TailRetention(warmup=5),
                        keeper_capacity=2)
    store2.put(_finished("err1", status="error"))
    store2.pin("err1")
    for i in range(30):
        store2.put(_finished(f"e{i}", status="error"))
    assert store2.get_all("err1"), "pinned upgrade evicted"
    assert store2.stats()["keep_reasons"]["pinned"] == 1
    # Bad ids don't pin.
    assert not store2.pin("")
    assert not store2.pin(None)


def test_retention_scoring_reasons():
    ret = TailRetention(tail_q=90.0, warmup=10, rare_below=2,
                        baseline_every=7)
    assert ret.score(_finished("a", status="timeout")) == "error"
    assert ret.score(_finished("b", slo=True)) == "slo"
    # First completions of a NEW (tenant, kind) pair are rare-kept.
    assert ret.score(_finished("c", tenant="newbie")) == "rare"
    assert ret.score(_finished("d", tenant="newbie")) == "rare"
    assert ret.score(_finished("e", tenant="newbie")) is None
    # Warm the per-kind latency histogram with healthy 10ms traffic,
    # then a 10x outlier scores as tail.
    for i in range(20):
        ret.score(_finished(f"w{i}", tenant="bulk2", latency=0.01))
    assert ret.score(_finished("slow", tenant="bulk2",
                               latency=0.5)) == "tail"
    # The deterministic 1-in-N counter baseline fires eventually.
    # Latency-free records (score/embed style) cannot score as tail, so
    # a fresh pair's keeps are exactly rare x2 then the 1-in-7 counter.
    reasons = [ret.score(_finished(f"h{i}", tenant="bulkz",
                                   latency=None)) for i in range(14)]
    assert "baseline" in reasons
    assert ret.stats()["seen"] > 30
    with pytest.raises(ValueError, match="tail_q"):
        TailRetention(tail_q=100.0)


def test_flight_dump_carries_wide_event_tail(tmp_path):
    from distkeras_tpu.telemetry import FlightRecorder, load_flight_dump

    store = WideEventStore(capacity=8)
    for i in range(3):
        store.append({"trace_id": f"t{i}", "tenant": "a",
                      "latency_s": 0.01})
    fr = FlightRecorder(capacity=4, wide_events=store,
                        dump_path=str(tmp_path / "box.json"), source="r9")
    fr.record_event("boot")
    dump = load_flight_dump(fr.dump())
    assert [r["trace_id"] for r in dump["wide_events_tail"]] \
        == ["t0", "t1", "t2"]
    assert dump["wide_events_stats"]["appended"] == 3
    # No store attached -> no wide keys, and dumping still works.
    fr2 = FlightRecorder(capacity=4,
                         dump_path=str(tmp_path / "box2.json"))
    fr2.record_event("boot")
    assert "wide_events_tail" not in load_flight_dump(fr2.dump())


# -- engine + server (jax lane) ----------------------------------------------

def test_engine_emits_wide_events_queryz_auditor_silent(rng, artifact_dir):
    """Every finished request = exactly one wide event; the queryz verb
    answers over the wire with mergeable payloads; and the ARMED auditor
    proves the analytics plane adds zero recompiles — with the snapshot
    dumped into the CI failure-artifact dir."""
    from distkeras_tpu.models.bert import gpt_tiny
    from distkeras_tpu.serving import ServingClient, ServingEngine
    from distkeras_tpu.serving.client import ServerError
    from distkeras_tpu.serving.server import ServingServer
    from distkeras_tpu.telemetry import RecompileAuditor

    model = gpt_tiny(seq_len=32, vocab_size=64)
    engine = ServingEngine(
        model, model.init(0), slots=2, max_queue=8,
        auditor=RecompileAuditor(), arm_auditor_after_warmup=True)
    assert engine.wide_events is not None  # default ON

    def prompt(n):
        return rng.integers(0, 64, size=(n,)).tolist()

    async def go():
        server = ServingServer(engine, port=0)
        await server.start()
        try:
            async with ServingClient("127.0.0.1", server.port) as c:
                for i in range(4):
                    await c.generate(prompt(4 + i), 3,
                                     tenant="a" if i % 2 else "b")
                out = await c.queryz(group_by=["tenant"],
                                     aggs=["count", "p50:latency_s",
                                           "mean:output_tokens"])
                health = await c.healthz()
                with pytest.raises(ServerError, match="unknown column"):
                    await c.queryz(where=["bogus=1"])
            return out, health
        finally:
            await server.stop(drain=True)

    out, health = asyncio.run(go())
    assert out["matched"] == 4 and out["stats"]["appended"] == 4
    by_tenant = {g["key"]["tenant"]: g for g in out["groups"]}
    assert by_tenant["a"]["count"] == 2 and by_tenant["b"]["count"] == 2
    for g in by_tenant.values():
        assert g["aggs"]["mean:output_tokens"]["value"] == pytest.approx(3)
        assert g["aggs"]["p50:latency_s"]["value"] > 0
        assert g["aggs"]["p50:latency_s"]["state"]["count"] == g["count"]
    assert health["wide_events"]["appended"] == 4

    # Ring rows carry the engine's identity + per-request story.
    tail = engine.wide_events.tail(4)
    assert all(r["status"] == "ok" and r["kind"] == "generate"
               and r["output_tokens"] == 3 and r["latency_s"] > 0
               for r in tail)
    assert {r["tenant"] for r in tail} == {"a", "b"}

    # THE invariant: analytics on, decode compiled exactly once.
    assert engine.auditor.compiles("serving_decode") == 1
    assert engine.auditor.report()["serving_decode"]["armed"]
    with open(artifact_dir / "queryz-snapshot.json", "w") as f:
        json.dump(out, f, indent=1)


# -- router fan-out over a jax-free Echo fleet --------------------------------

def test_router_queryz_fans_out_and_merges_echo_fleet():
    """Fleet queryz over real TCP: 2 Echo replicas, deterministic
    synthetic latencies (1 ms x prompt length), group-by percentiles
    recomputed offline from the prompts sent must match the merged
    fleet result within one histogram bucket width."""
    from distkeras_tpu.serving import ServingClient, ServingCluster
    from distkeras_tpu.serving.cluster.replicas import EchoReplica
    from distkeras_tpu.telemetry import MetricsRegistry

    prompts = {"a": [list(range(5, 5 + 3 + i)) for i in range(8)],
               "b": [list(range(2, 2 + 6 + 2 * i)) for i in range(5)]}

    async def go():
        cluster = ServingCluster(lambda i: EchoReplica(), 2,
                                 supervisor_kwargs=SUP,
                                 registry=MetricsRegistry())
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port) as c:
                for tenant, plist in prompts.items():
                    for p in plist:
                        await c.generate(p, 1, tenant=tenant)
                merged = await c.queryz(
                    group_by=["tenant"],
                    aggs=["count", "p99:latency_s", "mean:latency_s",
                          "sum:prompt_tokens"])
                pinned = await c.pin_traces(["abc123", "def456"])
        return merged, pinned, cluster

    merged, pinned, cluster = asyncio.run(go())
    assert merged["merged_from"] == 2
    assert set(merged["replicas"]) == {"r0", "r1"}
    assert all("matched" in sub for sub in merged["replicas"].values())
    n_total = sum(len(v) for v in prompts.values())
    assert merged["matched"] == n_total

    by_tenant = {g["key"]["tenant"]: g for g in merged["groups"]}
    for tenant, plist in prompts.items():
        g = by_tenant[tenant]
        assert g["count"] == len(plist)
        # Echo latency is exactly 0.001 * len(prompt): recompute the
        # fleet aggregate offline from what we sent.
        lats = [0.001 * len(p) for p in plist]
        assert g["aggs"]["mean:latency_s"]["value"] == pytest.approx(
            sum(lats) / len(lats))
        assert g["aggs"]["sum:prompt_tokens"]["value"] == pytest.approx(
            sum(len(p) for p in plist))
        p99 = g["aggs"]["p99:latency_s"]["value"]
        truth = _percentile(lats, 99)
        assert _bucket_width_ok(p99, truth), (tenant, p99, truth)

    # The front-port pin fanned out to every Echo's real TraceStore.
    assert pinned["pinned"] == ["abc123", "def456"]


def test_router_queryz_bad_request_and_pretty_print():
    """A typo'd spec comes back TYPED through the fan-out (every replica
    rejected it the same way), and format_queryz renders the merged
    page with group rows + replica notes."""
    from distkeras_tpu.serving import ServingClient, ServingCluster
    from distkeras_tpu.serving.client import ServerError
    from distkeras_tpu.serving.cluster.replicas import EchoReplica
    from distkeras_tpu.serving.debugz import format_queryz
    from distkeras_tpu.telemetry import MetricsRegistry

    async def go():
        cluster = ServingCluster(lambda i: EchoReplica(), 2,
                                 supervisor_kwargs=SUP,
                                 registry=MetricsRegistry())
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port) as c:
                for i in range(3):
                    await c.generate([7, 8, 9], 1, tenant="t")
                merged = await c.queryz(group_by=["tenant"],
                                        aggs=["count", "p50:latency_s"])
                with pytest.raises(ServerError, match="unknown column"):
                    await c.queryz(where=["no_such_col=1"])
                with pytest.raises(ServerError, match="capped at 2"):
                    await c.queryz(group_by=["tenant", "kind", "replica"])
        return merged

    merged = asyncio.run(go())
    page = format_queryz(merged)
    assert "queryz: matched 3 of 3 events (merged from 2 replica(s))" \
        in page
    assert "tenant" in page and "p50:latency_s" in page
    # A page with an unreachable replica names it.
    merged["replicas"]["r9"] = {"unreachable": "connection refused"}
    assert "replica r9: NOT MERGED — connection refused" \
        in format_queryz(merged)
    # Empty result renders, too.
    empty = format_queryz({"matched": 0, "scanned": 0, "groups": []})
    assert "(no matching events)" in empty


def test_router_pins_slo_page_exemplars_fleet_wide():
    """An SLO page event's exemplar trace ids get pinned into the
    router's own store AND fanned out to every replica's — idempotent
    across re-evaluations — and sloz reports them."""
    from distkeras_tpu.serving import ServingClient, ServingCluster
    from distkeras_tpu.serving.cluster.replicas import EchoReplica
    from distkeras_tpu.telemetry import MetricsRegistry

    async def go():
        cluster = ServingCluster(lambda i: EchoReplica(), 2,
                                 supervisor_kwargs=SUP,
                                 registry=MetricsRegistry())
        async with cluster:
            router = cluster.router
            # Inject a page transition the way the burn engine records
            # one (evaluate() appends the same shape).
            router.slo.events.append(
                {"t": 1.0, "objective": "ttft", "from": "warn",
                 "to": "page", "fast_burn": 20.0, "slow_burn": 8.0,
                 "exemplars": ["feedf00d00000001", "feedf00d00000002"]})
            fresh = await router._pin_slo_exemplars()
            again = await router._pin_slo_exemplars()  # idempotent
            async with ServingClient("127.0.0.1", cluster.port) as c:
                slo = await c._control({"cmd": "sloz"}, retry=True)
            echo_stats = [
                cluster.replicas[rid].handle.server.trace_store.stats()
                for rid in ("r0", "r1")]
        return fresh, again, slo["sloz"], echo_stats, router

    fresh, again, sloz, echo_stats, router = asyncio.run(go())
    assert sorted(fresh) == ["feedf00d00000001", "feedf00d00000002"]
    assert again == []
    assert router.trace_store.pinned() == sorted(fresh)
    assert sloz["pinned_exemplars"] == sorted(fresh)
    # Every Echo replica's REAL TraceStore holds the pins.
    for st in echo_stats:
        assert st["pinned"] == 2


# -- CLI ----------------------------------------------------------------------

def test_queryz_cli_json_and_pretty():
    """`run.py queryz` against a live (jax-free Echo) server: --json
    prints the payload, the default prints the table, a typo'd --where
    comes back as a nonzero exit with the typed message."""
    from distkeras_tpu.run import queryz_main
    from distkeras_tpu.serving.cluster.replicas import EchoServer

    started = threading.Event()
    holder: dict = {}

    def serve_forever():
        async def go():
            server = EchoServer()
            await server.start()
            for i in range(5):
                server._reply({"prompt": [3] * (i + 2), "max_new_tokens": 1,
                               "tenant": "cli", "trace_id": f"c{i}"})
            holder["port"] = server.port
            holder["stop"] = asyncio.Event()
            started.set()
            await holder["stop"].wait()
            await server.stop()

        holder["loop"] = asyncio.new_event_loop()
        holder["loop"].run_until_complete(go())

    t = threading.Thread(target=serve_forever, daemon=True)
    t.start()
    assert started.wait(30)
    try:
        args = ["--host", "127.0.0.1", "--port", str(holder["port"])]
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = queryz_main(args + ["--group-by", "tenant,kind",
                                     "--agg", "count",
                                     "--agg", "p99:latency_s", "--json"])
        assert rc == 0
        payload = json.loads(buf.getvalue())
        assert payload["matched"] == 5
        assert payload["group_by"] == ["tenant", "kind"]
        assert payload["groups"][0]["key"] == {"tenant": "cli",
                                               "kind": "generate"}

        buf2 = io.StringIO()
        with contextlib.redirect_stdout(buf2):
            assert queryz_main(args + ["--where", "kind=generate",
                                       "--group-by", "tenant"]) == 0
        assert "queryz: matched 5 of 5 events" in buf2.getvalue()
        assert "cli" in buf2.getvalue()

        err = io.StringIO()
        with contextlib.redirect_stderr(err):
            assert queryz_main(args + ["--where", "bogus=1"]) == 1
        assert "unknown column" in err.getvalue()
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=30)


# -- slow lane: real child processes ------------------------------------------

@pytest.mark.slow
def test_process_cluster_queryz_end_to_end(rng):
    """Fleet analytics on the real deployment shape: `run.py serve`
    children behind the router, wide events emitted by real engines,
    queryz merged across processes over the wire."""
    from distkeras_tpu.serving import ServingClient, ServingCluster
    from distkeras_tpu.serving.cluster import ProcessReplica

    prompts = [rng.integers(0, 64, size=(4 + i % 3,)).tolist()
               for i in range(6)]

    async def go():
        extra = ["--model", "gpt_tiny",
                 "--model-args", '{"seq_len": 32, "vocab_size": 64}',
                 "--slots", "2", "--seed", "0"]
        cluster = ServingCluster(lambda i: ProcessReplica(extra), 2,
                                 supervisor_kwargs=dict(
                                     health_interval_s=0.5))
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port) as c:
                for i, p in enumerate(prompts):
                    await c.generate(p, 2, tenant=f"t{i % 2}")
                merged = await c.queryz(
                    where=["status=ok"], group_by=["tenant"],
                    aggs=["count", "p50:latency_s"])
        return merged

    merged = asyncio.run(go())
    assert merged["merged_from"] == 2
    assert merged["matched"] == len(prompts)
    by_tenant = {g["key"]["tenant"]: g["count"]
                 for g in merged["groups"]}
    assert by_tenant == {"t0": 3, "t1": 3}
