"""Fused softmax cross-entropy kernel vs optax reference (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distkeras_tpu.ops.pallas.fused_xent import fused_softmax_xent


def _data(rng, T=64, V=512):
    logits = np.asarray(rng.normal(size=(T, V)) * 3, np.float32)
    labels = rng.integers(0, V, size=T).astype(np.int32)
    return logits, labels


def test_loss_matches_optax(rng):
    logits, labels = _data(rng)
    got = float(fused_softmax_xent(logits, labels, block_t=16, block_v=128))
    ref = float(
        optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_sequence_shaped_inputs(rng):
    B, S, V = 2, 16, 256
    logits = np.asarray(rng.normal(size=(B, S, V)), np.float32)
    labels = rng.integers(0, V, size=(B, S)).astype(np.int32)
    got = float(fused_softmax_xent(logits, labels, block_t=8, block_v=64))
    ref = float(
        optax.softmax_cross_entropy_with_integer_labels(
            logits.reshape(-1, V), labels.reshape(-1)
        ).mean()
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_gradients_match_optax(rng):
    logits, labels = _data(rng, T=32, V=256)

    g_fused = jax.grad(
        lambda l: fused_softmax_xent(l, labels, block_t=8, block_v=64)
    )(logits)
    g_ref = jax.grad(
        lambda l: optax.softmax_cross_entropy_with_integer_labels(l, labels).mean()
    )(logits)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               atol=1e-6, rtol=1e-4)


@pytest.mark.slow
def test_registered_loss_trains(rng):
    """'fused_categorical_crossentropy' works through the trainer stack."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import bert_tiny_mlm

    vocab, seq = 128, 16
    tokens = rng.integers(1, vocab, size=(128, seq)).astype(np.int32)
    ds = dk.Dataset.from_arrays(features=tokens, label=tokens)
    trainer = dk.SingleTrainer(
        bert_tiny_mlm(seq_len=seq, vocab_size=vocab),
        worker_optimizer="adam", learning_rate=1e-3,
        loss="fused_categorical_crossentropy",
        batch_size=16, num_epoch=2,
    )
    trainer.train(ds)
    hist = trainer.get_history()
    assert hist[-1]["loss"] < hist[0]["loss"]


def test_bf16_logits(rng):
    import ml_dtypes

    logits, labels = _data(rng, T=32, V=256)
    got = float(fused_softmax_xent(logits.astype(ml_dtypes.bfloat16), labels,
                                   block_t=8, block_v=64))
    ref = float(
        optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(ml_dtypes.bfloat16).astype(np.float32), labels
        ).mean()
    )
    np.testing.assert_allclose(got, ref, rtol=1e-3)


def test_odd_vocab_real_sizes(rng):
    """30522-style vocab must keep full-width tiles via padding (no
    degenerate block shrink) and still match optax."""
    T, V = 16, 1003  # deliberately prime-ish, indivisible by any block
    logits = np.asarray(rng.normal(size=(T, V)) * 2, np.float32)
    labels = rng.integers(0, V, size=T).astype(np.int32)
    got = float(fused_softmax_xent(logits, labels, block_t=8, block_v=128))
    ref = float(optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean())
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    g = jax.grad(lambda l: fused_softmax_xent(l, labels, block_t=8, block_v=128))(logits)
    gr = jax.grad(lambda l: optax.softmax_cross_entropy_with_integer_labels(l, labels).mean())(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-6, rtol=1e-4)
