"""CLI runner tests (the Job/Punchcard payload format)."""

import json
import subprocess
import sys

import numpy as np
import pytest


@pytest.fixture
def job(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 28)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    data = tmp_path / "d.npz"
    np.savez(data, features=x, label=y)
    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "trainer": "DOWNPOUR", "worker_optimizer": "adam",
        "learning_rate": 0.01, "num_workers": 2, "batch_size": 16,
        "num_epoch": 2, "communication_window": 4,
    }))
    return data, cfg, tmp_path


@pytest.mark.slow
def test_cli_end_to_end(job):
    data, cfg, tmp = job
    out = tmp / "weights.bin"
    metrics = tmp / "metrics.jsonl"
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms','cpu');"
         "from distkeras_tpu.run import main; import sys; sys.exit(main())",
         "--config", str(cfg), "--data", str(data), "--model", "higgs_mlp",
         "--out", str(out), "--metrics-out", str(metrics), "--shuffle"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["trainer"] == "DOWNPOUR"
    assert summary["steps"] > 0
    assert out.exists()
    lines = [json.loads(l) for l in open(metrics)]
    assert len(lines) == summary["steps"]


def test_cli_train_statusz_and_stamped_out(job, capsys):
    """In-process `run.py train`: the statusz writer leaves a final
    worker-table snapshot, the summary line carries staleness/goodput,
    --out is provenance-stamped, and the `statusz` subcommand renders
    the snapshot for humans."""
    data, cfg, tmp = job
    out = tmp / "weights.bin"
    statusz = tmp / "statusz.json"
    from distkeras_tpu.run import main

    rc = main(["train", "--config", str(cfg), "--data", str(data),
               "--model", "higgs_mlp", "--out", str(out),
               "--statusz-out", str(statusz),
               "--statusz-interval", "0.2"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["statusz"] == str(statusz)
    assert "staleness_p99" in summary and "goodput_ratio" in summary
    payload = json.loads(statusz.read_text())
    assert {w["worker"] for w in payload["workers"]} == {0, 1}
    assert payload["ps"]["num_commits"] >= 2
    # The saved weights carry the provenance stamp serve/reload read.
    from distkeras_tpu.checkpoint import load_weights_meta

    assert load_weights_meta(str(out))["version"] == 1

    rc = main(["statusz", "--file", str(statusz)])
    assert rc == 0
    page = capsys.readouterr().out
    assert "workers:" in page and "staleness:" in page


def test_serving_config_flags_forwarded_to_replicas():
    """The ONE replica-flag builder shared by `cluster` and `deploy`:
    paged/chunk/speculation configuration reaches every replica child —
    which is what makes deploy's canary validate candidates under the
    fleet's REAL serving config instead of the dense one-token
    default."""
    import argparse

    from distkeras_tpu.run import _serving_config_flags

    args = argparse.Namespace(
        top_k=8, prefill_chunk=32, prefix_cache_mb=0.0, prefix_block=16,
        paged=True, kv_pool_mb=64.0, kv_block_tokens=8, max_context=48,
        draft_model="gpt_tiny", draft_args='{"seq_len": 64}',
        draft_weights=None, spec_k=6)
    flags = _serving_config_flags(args)
    for pair in (["--paged"], ["--kv-pool-mb", "64.0"],
                 ["--kv-block-tokens", "8"], ["--prefill-chunk", "32"],
                 ["--max-context", "48"], ["--top-k", "8"],
                 ["--draft-model", "gpt_tiny"],
                 ["--draft-args", '{"seq_len": 64}'], ["--spec-k", "6"]):
        joined = " ".join(flags)
        assert " ".join(pair) in joined, (pair, flags)
    # Dense default: no paged/spec flags leak into the children.
    plain = argparse.Namespace(
        top_k=None, prefill_chunk=None, prefix_cache_mb=0.0,
        prefix_block=16, paged=False, kv_pool_mb=0.0, kv_block_tokens=16,
        max_context=None, draft_model=None, draft_args="{}",
        draft_weights=None, spec_k=4)
    flags = _serving_config_flags(plain)
    assert "--paged" not in flags and "--draft-model" not in flags

    assert all(isinstance(f, str) for f in _serving_config_flags(args))


def test_deploy_and_serve_parsers_accept_serving_config(capsys):
    """Every flag the replica builder emits must exist on BOTH parent
    parsers — a flag deploy's parser rejects could never reach its
    canary replicas."""
    import pytest as _pytest

    from distkeras_tpu.run import deploy_main, serve_main

    for main_fn, argv in ((deploy_main, ["--help"]),
                          (serve_main, ["--help"])):
        with _pytest.raises(SystemExit) as e:
            main_fn(argv)
        assert e.value.code == 0
        text = capsys.readouterr().out
        for flag in ("--draft-model", "--draft-args", "--spec-k",
                     "--paged", "--kv-pool-mb", "--kv-block-tokens",
                     "--prefill-chunk", "--prefix-cache-mb",
                     "--max-context", "--mesh", "--mesh-shape",
                     "--force-host-devices"):
            assert flag in text, (main_fn.__name__, flag)


def test_serve_mesh_shape_typed_cli_errors():
    """A --mesh-shape that can't parse, or whose device product does
    not divide the visible device count, must die as ONE typed CLI
    line (SystemExit) before any server/engine work — never a deep jax
    traceback."""
    import jax
    import pytest as _pytest

    from distkeras_tpu.run import serve_main

    n = len(jax.devices())
    with _pytest.raises(SystemExit) as e:
        serve_main(["--mesh-shape", f"tp={n + 1}", "--port", "0"])
    assert "divide" in str(e.value) and "--mesh" in str(e.value)
    with _pytest.raises(SystemExit) as e:
        serve_main(["--mesh-shape", "tp=banana", "--port", "0"])
    assert "--mesh-shape" in str(e.value)
    # A mesh shape with no tp axis is equally typed.
    with _pytest.raises(SystemExit) as e:
        serve_main(["--mesh-shape", "dp=1", "--port", "0"])
    assert "tp" in str(e.value)


def test_mesh_flags_forwarded_to_replicas():
    """Cluster/deploy children must inherit the parent's sharding ask:
    the shared flag builder forwards --mesh/--mesh-shape (and the
    forced device count) to every replica."""
    import argparse

    from distkeras_tpu.run import _serving_config_flags

    base = dict(
        top_k=None, prefill_chunk=None, prefix_cache_mb=0.0,
        prefix_block=16, paged=False, kv_pool_mb=0.0, kv_block_tokens=16,
        max_context=None, draft_model=None, draft_args="{}",
        draft_weights=None, spec_k=4)
    shaped = argparse.Namespace(**base, mesh=False, mesh_shape="tp=2",
                                force_host_devices=2)
    flags = " ".join(_serving_config_flags(shaped))
    assert "--mesh-shape tp=2" in flags
    assert "--force-host-devices 2" in flags
    bare = argparse.Namespace(**base, mesh=True, mesh_shape=None,
                              force_host_devices=None)
    flags = _serving_config_flags(bare)
    assert "--mesh" in flags and "--mesh-shape" not in flags
    plain = argparse.Namespace(**base, mesh=False, mesh_shape=None,
                               force_host_devices=None)
    assert "--mesh" not in _serving_config_flags(plain)


def test_cli_unknown_model(job):
    data, cfg, _ = job
    r = subprocess.run(
        [sys.executable, "-m", "distkeras_tpu.run", "--config", str(cfg),
         "--data", str(data), "--model", "nope"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode != 0
    assert "unknown model" in r.stderr
