"""CLI runner tests (the Job/Punchcard payload format)."""

import json
import subprocess
import sys

import numpy as np
import pytest


@pytest.fixture
def job(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 28)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    data = tmp_path / "d.npz"
    np.savez(data, features=x, label=y)
    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "trainer": "DOWNPOUR", "worker_optimizer": "adam",
        "learning_rate": 0.01, "num_workers": 2, "batch_size": 16,
        "num_epoch": 2, "communication_window": 4,
    }))
    return data, cfg, tmp_path


@pytest.mark.slow
def test_cli_end_to_end(job):
    data, cfg, tmp = job
    out = tmp / "weights.bin"
    metrics = tmp / "metrics.jsonl"
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms','cpu');"
         "from distkeras_tpu.run import main; import sys; sys.exit(main())",
         "--config", str(cfg), "--data", str(data), "--model", "higgs_mlp",
         "--out", str(out), "--metrics-out", str(metrics), "--shuffle"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["trainer"] == "DOWNPOUR"
    assert summary["steps"] > 0
    assert out.exists()
    lines = [json.loads(l) for l in open(metrics)]
    assert len(lines) == summary["steps"]


def test_cli_unknown_model(job):
    data, cfg, _ = job
    r = subprocess.run(
        [sys.executable, "-m", "distkeras_tpu.run", "--config", str(cfg),
         "--data", str(data), "--model", "nope"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode != 0
    assert "unknown model" in r.stderr
