"""CLI runner tests (the Job/Punchcard payload format)."""

import json
import subprocess
import sys

import numpy as np
import pytest


@pytest.fixture
def job(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 28)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    data = tmp_path / "d.npz"
    np.savez(data, features=x, label=y)
    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "trainer": "DOWNPOUR", "worker_optimizer": "adam",
        "learning_rate": 0.01, "num_workers": 2, "batch_size": 16,
        "num_epoch": 2, "communication_window": 4,
    }))
    return data, cfg, tmp_path


@pytest.mark.slow
def test_cli_end_to_end(job):
    data, cfg, tmp = job
    out = tmp / "weights.bin"
    metrics = tmp / "metrics.jsonl"
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms','cpu');"
         "from distkeras_tpu.run import main; import sys; sys.exit(main())",
         "--config", str(cfg), "--data", str(data), "--model", "higgs_mlp",
         "--out", str(out), "--metrics-out", str(metrics), "--shuffle"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["trainer"] == "DOWNPOUR"
    assert summary["steps"] > 0
    assert out.exists()
    lines = [json.loads(l) for l in open(metrics)]
    assert len(lines) == summary["steps"]


def test_cli_train_statusz_and_stamped_out(job, capsys):
    """In-process `run.py train`: the statusz writer leaves a final
    worker-table snapshot, the summary line carries staleness/goodput,
    --out is provenance-stamped, and the `statusz` subcommand renders
    the snapshot for humans."""
    data, cfg, tmp = job
    out = tmp / "weights.bin"
    statusz = tmp / "statusz.json"
    from distkeras_tpu.run import main

    rc = main(["train", "--config", str(cfg), "--data", str(data),
               "--model", "higgs_mlp", "--out", str(out),
               "--statusz-out", str(statusz),
               "--statusz-interval", "0.2"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["statusz"] == str(statusz)
    assert "staleness_p99" in summary and "goodput_ratio" in summary
    payload = json.loads(statusz.read_text())
    assert {w["worker"] for w in payload["workers"]} == {0, 1}
    assert payload["ps"]["num_commits"] >= 2
    # The saved weights carry the provenance stamp serve/reload read.
    from distkeras_tpu.checkpoint import load_weights_meta

    assert load_weights_meta(str(out))["version"] == 1

    rc = main(["statusz", "--file", str(statusz)])
    assert rc == 0
    page = capsys.readouterr().out
    assert "workers:" in page and "staleness:" in page


def test_cli_unknown_model(job):
    data, cfg, _ = job
    r = subprocess.run(
        [sys.executable, "-m", "distkeras_tpu.run", "--config", str(cfg),
         "--data", str(data), "--model", "nope"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode != 0
    assert "unknown model" in r.stderr
