"""Real-data acceptance (VERDICT r1 missing item 2): the reference's only
correctness criterion was "distributed accuracy ≈ the single-node run on
real data" (SURVEY §4). sklearn bundles the UCI digits set offline — 1797
real 8x8 handwritten-digit images — so the criterion is testable without
network egress."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # integration-scale; run with `pytest -m ''`

pytest.importorskip("sklearn")

import distkeras_tpu as dk
from distkeras_tpu.data.transformers import MinMaxTransformer
from distkeras_tpu.inference.evaluators import AccuracyEvaluator
from distkeras_tpu.inference.predictors import ModelPredictor
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP


@pytest.fixture(scope="module")
def digits():
    from sklearn.datasets import load_digits

    d = load_digits()
    ds = dk.Dataset.from_arrays(
        features=d.data.astype(np.float32), label=d.target.astype(np.float32)
    )
    ds = MinMaxTransformer(min=0, max=16, output_col="features").transform(ds)
    ds = ds.shuffle(seed=0)
    return ds.slice(0, 1500), ds.slice(1500, len(ds))


def _model():
    return Model.from_flax(MLP(features=(64, 64), num_classes=10), input_shape=(64,))


def _accuracy(trained, test):
    pred = ModelPredictor(trained).predict(test)
    return AccuracyEvaluator(prediction_col="prediction", label_col="label").evaluate(
        pred
    )


def test_real_digits_single_node_learns(digits):
    train, test = digits
    t = dk.SingleTrainer(_model(), worker_optimizer="adam", learning_rate=1e-3,
                         batch_size=32, num_epoch=20, seed=0)
    trained = t.train(train, shuffle=True)
    acc = _accuracy(trained, test)
    assert acc > 0.93, acc


def test_real_digits_async_parity_with_single(digits):
    """The reference acceptance criterion, on real data."""
    train, test = digits
    kwargs = dict(worker_optimizer="adam", learning_rate=1e-3, batch_size=32,
                  num_epoch=20, seed=0)
    single = dk.SingleTrainer(_model(), **kwargs)
    acc_single = _accuracy(single.train(train, shuffle=True), test)
    adag = dk.ADAG(_model(), num_workers=4, **kwargs)
    acc_adag = _accuracy(adag.train(train, shuffle=True), test)
    assert acc_single > 0.93
    assert abs(acc_adag - acc_single) < 0.08, (acc_adag, acc_single)
