"""Real-data acceptance (VERDICT r1 missing item 2): the reference's only
correctness criterion was "distributed accuracy ≈ the single-node run on
real data" (SURVEY §4). sklearn bundles the UCI digits set offline — 1797
real 8x8 handwritten-digit images — so the criterion is testable without
network egress."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # integration-scale; run with `pytest -m ''`

pytest.importorskip("sklearn")

import distkeras_tpu as dk
from distkeras_tpu.data.transformers import MinMaxTransformer
from distkeras_tpu.inference.evaluators import AccuracyEvaluator
from distkeras_tpu.inference.predictors import ModelPredictor
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP


@pytest.fixture(scope="module")
def digits():
    from sklearn.datasets import load_digits

    d = load_digits()
    ds = dk.Dataset.from_arrays(
        features=d.data.astype(np.float32), label=d.target.astype(np.float32)
    )
    ds = MinMaxTransformer(min=0, max=16, output_col="features").transform(ds)
    ds = ds.shuffle(seed=0)
    return ds.slice(0, 1500), ds.slice(1500, len(ds))


def _model():
    return Model.from_flax(MLP(features=(64, 64), num_classes=10), input_shape=(64,))


def _accuracy(trained, test):
    pred = ModelPredictor(trained).predict(test)
    return AccuracyEvaluator(prediction_col="prediction", label_col="label").evaluate(
        pred
    )


_KWARGS = dict(worker_optimizer="adam", learning_rate=1e-3, batch_size=32,
               num_epoch=20, seed=0)


@pytest.fixture(scope="module")
def single_acc(digits):
    """The single-node baseline, trained ONCE for the whole module (every
    parity test compares against the same run)."""
    train, test = digits
    single = dk.SingleTrainer(_model(), **_KWARGS)
    return _accuracy(single.train(train, shuffle=True), test)


def test_real_digits_single_node_learns(single_acc):
    assert single_acc > 0.93, single_acc


def test_real_digits_async_parity_with_single(digits, single_acc):
    """The reference acceptance criterion, on real data."""
    train, test = digits
    adag = dk.ADAG(_model(), num_workers=4, **_KWARGS)
    acc_adag = _accuracy(adag.train(train, shuffle=True), test)
    assert abs(acc_adag - single_acc) < 0.08, (acc_adag, single_acc)


@pytest.mark.parametrize("cls", ["AEASGD", "EAMSGD"])
def test_real_digits_elastic_parity_with_single(digits, single_acc, cls):
    """The elastic family on real data (round 5 — completes the acceptance
    matrix, EAMSGD included). alpha = rho*lr is the CENTER's tracking rate
    and the returned model IS the center: with adam-scale lr (1e-3), rho
    must scale up to land alpha in a working band (rho=50 -> alpha=0.05;
    measured: rho=1 -> alpha=1e-3 leaves the center at 0.15 accuracy) —
    the footgun is documented on the trainer."""
    train, test = digits
    elastic = getattr(dk, cls)(_model(), num_workers=4, rho=50.0,
                               communication_window=8, **_KWARGS)
    acc_elastic = _accuracy(elastic.train(train, shuffle=True), test)
    assert abs(acc_elastic - single_acc) < 0.08, (acc_elastic, single_acc)
