"""Paged KV memory: one block pool for decode slots AND the prefix cache.

The invariants under test, all on CPU with a tiny causal LM:

- paged greedy streams are token-identical to offline ``generate()`` AND
  to the dense-cache engine, including prefix-cache hit/miss and
  evict-round-trip cases (the paged pool doubles as the prefix cache);
- the single-compiled-decode-step invariant survives paging: an ARMED
  ``RecompileAuditor`` stays silent across admissions, block-table
  growth, preemptions, and long-context requests;
- oversubscription: a pool sized to force preemption under load still
  completes every request token-identically (preempt -> adopt blocks ->
  requeue -> resume prefill folds streamed tokens back in), and the
  request's timeline shows both admission hops under one trace_id;
- long-context admission: a request longer than a dense engine's padded
  max (same byte budget) is served to completion because blocks chain
  on demand instead of being pre-reserved;
- requests that can NEVER fit the pool are rejected with the typed
  ``kv_oom`` error at submit, before any device work;
- pool health is observable: ``kv_pool_blocks_{total,used,free}``
  gauges, ``kv_preemptions_total`` / ``kv_oom_rejections_total``
  counters, and per-slot block-table depth in ``debugz``.

``KVBlockPool`` unit behavior (alloc/free/adopt/match, model-free) rides
along at the top — it is the host-side allocator everything above
leans on.
"""

import asyncio

import numpy as np
import pytest

from distkeras_tpu.inference.generate import generate
from distkeras_tpu.models.bert import gpt_tiny
from distkeras_tpu.serving import (
    KVBlockPool,
    PoolExhausted,
    ServingEngine,
    ServingMetrics,
)

VOCAB = 64


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(0)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


def _want(lm, prompt, n):
    model, variables = lm
    return generate(model, variables, np.asarray([prompt], np.int32), n,
                    greedy=True)[0].tolist()


async def _run_engine(engine, coro):
    task = asyncio.create_task(engine.run())
    try:
        return await coro
    finally:
        engine.shutdown(drain=True)
        await task


# -- KVBlockPool unit behavior (model-free) ----------------------------------

def test_block_pool_alloc_is_all_or_nothing():
    pool = KVBlockPool(4, 2)
    got = pool.alloc(3)
    assert len(got) == 3 and pool.blocks_free == 1
    # Shortfall: nothing is kept, the partial grant is rolled back.
    assert pool.alloc(2) is None
    assert pool.blocks_free == 1
    pool.free(got)
    assert pool.blocks_free == 4


def test_block_pool_adopt_then_match_zero_copy():
    """A finished slot's complete blocks become trie nodes IN PLACE: a
    later match returns the same pool row ids (no store copy), pinned so
    alloc-side eviction cannot reallocate them."""
    pool = KVBlockPool(4, 2)
    ids = pool.alloc(3)
    tokens = [1, 2, 3, 4, 5]  # blocks (1,2), (3,4); 5 is incomplete
    adopted = pool.adopt(tokens, ids, 0)
    assert adopted == 2
    # The incomplete tail block's row went back to the free list.
    assert pool.blocks_free == 2
    m = pool.match([1, 2, 3, 4, 9, 9])
    assert m.matched_tokens == 4
    assert list(m.ids) == [int(i) for i in ids[:2]]  # the SAME rows
    # Pinned rows survive allocation pressure (all-or-nothing fails
    # rather than evicting a pinned chain).
    assert pool.alloc(3) is None
    pool.release(m)
    assert len(pool.alloc(3)) == 3  # now the LRU chain was evictable


def test_block_pool_adopt_duplicate_frees_loser():
    """Two slots computing the same prefix: the second adoption keeps the
    cached copy and frees its duplicate rows."""
    pool = KVBlockPool(4, 2)
    a = pool.alloc(1)
    assert pool.adopt([1, 2], a, 0) == 1
    b = pool.alloc(1)
    assert pool.adopt([1, 2], b, 0) == 0  # duplicate: cached copy wins
    assert pool.blocks_free == 3  # b's row was freed, a's retained
    assert pool.match([1, 2, 7]).matched_tokens == 2


def test_block_pool_version_moves_on_free_and_adopt():
    """The engine's admission-parking heuristic watches ``version``: it
    must move whenever blocks become free or evictable."""
    pool = KVBlockPool(4, 2)
    v0 = pool.version
    ids = pool.alloc(2)
    pool.free(ids)
    assert pool.version > v0
    v1 = pool.version
    ids = pool.alloc(1)
    pool.adopt([1, 2], ids, 0)
    assert pool.version > v1


def _pool_with_two_cached_chains():
    """A full 4-block pool whose rows all sit in unpinned trie chains
    ([1,2]->[1,2,3,4] and [5,6]->[5,6,7,8]): any further alloc must
    evict."""
    pool = KVBlockPool(4, 2)
    a = pool.alloc(2)
    assert pool.adopt([1, 2, 3, 4], a, 0) == 2
    b = pool.alloc(2)
    assert pool.adopt([5, 6, 7, 8], b, 0) == 2
    assert pool.blocks_free == 0
    return pool


def test_block_pool_spill_many_batches_the_eviction_burst():
    """With ``spill_many_hook`` set, a multi-block alloc's eviction
    victims arrive in ONE call (the tiered engine turns that into one
    D2H gather) — and the batch matches the per-victim ``spill_hook``
    sequence exactly, victim for victim."""
    pool = _pool_with_two_cached_chains()
    batches: list[list] = []
    pool.spill_many_hook = lambda victims: batches.append(list(victims))
    # The batched hook takes precedence inside the burst: the
    # per-victim hook must stay silent.
    singles: list[tuple] = []
    pool.spill_hook = lambda chain, slot: singles.append((chain, slot))
    got = pool.alloc(4)
    assert got is not None and len(got) == 4
    assert len(batches) == 1 and len(batches[0]) == 4
    assert singles == []
    # Parity: the identically-built pool with ONLY the per-victim hook
    # spills the same (chain, slot) sequence, one call per victim.
    pool2 = _pool_with_two_cached_chains()
    pool2.spill_hook = lambda chain, slot: singles.append((chain, slot))
    assert pool2.alloc(4) is not None
    assert ([(list(c), s) for c, s in batches[0]]
            == [(list(c), s) for c, s in singles])
    # Every victim carried its full root->leaf chain.
    chains = sorted(tuple(c) for c, _ in batches[0])
    assert chains == [(1, 2), (1, 2, 3, 4), (5, 6), (5, 6, 7, 8)]


def test_block_pool_spill_burst_flushes_even_on_shortfall():
    """An alloc that fails midway already evicted its victims; the
    burst must still hand them to the spill tier (the rows go back to
    the free list unwritten, so the bytes are intact at flush time)."""
    pool = KVBlockPool(3, 2)
    a = pool.alloc(1)
    assert pool.adopt([1, 2], a, 0) == 1  # evictable chain
    b = pool.alloc(1)
    assert pool.adopt([3, 4], b, 0) == 1
    m = pool.match([3, 4, 9])  # pins [3,4]: unevictable
    assert m.matched_tokens == 2
    batches: list[list] = []
    pool.spill_many_hook = lambda victims: batches.append(list(victims))
    assert pool.alloc(3) is None  # 1 free + 1 evictable < 3
    assert len(batches) == 1
    assert [(list(c), s) for c, s in batches[0]] == [([1, 2], a[0])]
    pool.release(m)


# -- paged engine: parity + the compile invariant ----------------------------

def test_paged_parity_vs_generate_and_dense_with_armed_auditor(lm, rng):
    """THE tentpole invariant: paged greedy output is token-identical to
    offline generate() and to the dense-cache engine across staggered
    admissions into freed slots — and the ARMED auditor proves the
    decode step compiled exactly once while block tables changed under
    it every admission."""
    from distkeras_tpu.telemetry import RecompileAuditor

    model, variables = lm
    auditor = RecompileAuditor()
    paged = ServingEngine(model, variables, slots=2, max_queue=8,
                          kv_pool_blocks=64, kv_block_tokens=4,
                          auditor=auditor, arm_auditor_after_warmup=True)
    dense = ServingEngine(model, variables, slots=2, max_queue=8)
    prompts = [_prompt(rng, n) for n in (5, 9, 3, 7, 4)]

    async def work(engine):
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(engine.submit(p, 6))
            await asyncio.sleep(0.01 * i)  # arrive mid-decode, post-arming
        return [await r.result() for r in reqs]

    got_paged = asyncio.run(_run_engine(paged, work(paged)))
    got_dense = asyncio.run(_run_engine(dense, work(dense)))
    want = [_want(lm, p, 6) for p in prompts]
    assert got_paged == want
    assert got_dense == want
    assert auditor.compiles("serving_decode") == 1
    assert auditor.report()["serving_decode"]["armed"]
    assert paged.decode_compile_count() in (1, -1)
    # Slot teardown adopted every finished sequence's complete blocks
    # into the trie; nothing leaked to a non-free, non-trie limbo.
    assert paged.active_slots == 0
    assert all((t == paged._sentinel).all() for t in paged._tables)


def test_paged_prefix_hits_are_zero_copy_and_parity_exact(lm, rng):
    """Paged prefix caching is inherent: repeated prompt prefixes match
    the blocks ADOPTED from earlier slots (no store copy ever ran) and
    the hit's output stays token-identical, chunked admission included."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1, max_queue=16,
                           kv_pool_blocks=32, kv_block_tokens=4,
                           prefill_chunk=4)
    shared = _prompt(rng, 12)
    prompts = [shared + _prompt(rng, k) for k in (3, 4, 5, 3)]

    async def drive():
        outs = []
        for p in prompts:  # sequential: later prompts hit earlier ones
            outs.append(await engine.submit(p, 5).result())
        return outs

    outs = asyncio.run(_run_engine(engine, drive()))
    assert outs == [_want(lm, p, 5) for p in prompts]
    s = engine.kv_pool.stats()
    assert s["hit_requests"] >= 3  # every repeat matched the prefix
    assert s["hit_tokens"] >= 3 * 12
    # Zero-copy: blocks entered the trie by adoption, not a device store
    # (the paged engine has no store program at all).
    assert s["inserted_blocks"] > 0
    assert engine.decode_compile_count() in (1, -1)


def test_paged_hit_after_evict_round_trip(lm, rng):
    """Evicting a cached prefix under pool pressure costs performance,
    never correctness: A cached -> displaced by B/C -> A re-prefilled
    and re-adopted -> A hits again; parity holds throughout."""
    model, variables = lm
    # 5 blocks x 4 tokens: a finished 15-token sequence adopts 3 blocks,
    # so b's from-scratch admission (3 private + 1 growth) must evict
    # part of a's resident chain.
    engine = ServingEngine(model, variables, slots=1, max_queue=16,
                           kv_pool_blocks=5, kv_block_tokens=4)
    a, b = _prompt(rng, 11), _prompt(rng, 11)

    async def drive():
        outs = []
        for p in (a, a, b, a, a):  # hit, evict via b, miss, re-hit
            outs.append(await engine.submit(p, 4).result())
        return outs

    outs = asyncio.run(_run_engine(engine, drive()))
    wa, wb = _want(lm, a, 4), _want(lm, b, 4)
    assert outs == [wa, wa, wb, wa, wa]
    s = engine.kv_pool.stats()
    assert s["evicted_blocks"] > 0  # pressure really displaced blocks
    assert s["hit_requests"] >= 2


# -- oversubscription: preempt-and-requeue -----------------------------------

def test_preempt_and_requeue_completes_token_identical(lm, rng):
    """THE satellite invariant: a pool sized to force preemption under
    concurrent load must still complete every request with output
    token-identical to the unconstrained run — and the preempted
    request's timeline shows the preemption and BOTH admission hops
    under one trace_id."""
    from distkeras_tpu.telemetry import RecompileAuditor, TraceStore

    model, variables = lm
    auditor = RecompileAuditor()
    store = TraceStore()
    # 4 slots x (12-token prompt + 10 new) needs ~4 * 6 blocks at
    # completion; 13 blocks can hold ~2 full sequences, so concurrent
    # decode growth MUST preempt.
    tight = ServingEngine(model, variables, slots=4, max_queue=16,
                          kv_pool_blocks=13, kv_block_tokens=4,
                          trace_store=store, auditor=auditor,
                          arm_auditor_after_warmup=True)
    roomy = ServingEngine(model, variables, slots=4, max_queue=16,
                          kv_pool_blocks=64, kv_block_tokens=4)
    prompts = [_prompt(rng, 12) for _ in range(4)]

    async def work(engine):
        reqs = [engine.submit(p, 10) for p in prompts]
        return [await r.result() for r in reqs]

    got_tight = asyncio.run(_run_engine(tight, work(tight)))
    got_roomy = asyncio.run(_run_engine(roomy, work(roomy)))
    want = [_want(lm, p, 10) for p in prompts]
    assert got_tight == want, "preempt-and-requeue changed output"
    assert got_roomy == want
    assert tight.metrics.preemptions > 0, (
        "pool was supposed to be tight enough to force preemption")
    # The armed auditor held through every preemption + re-admission.
    assert auditor.compiles("serving_decode") == 1
    # The preempted request's merged timeline: one trace_id, a preempt
    # event, and an admission hop on EACH side of it.
    preempted = [rec for rec in store.recent(10)
                 if any(e[0] == "preempt" for e in rec["events"])]
    assert preempted, "no preempted request left a timeline"
    for rec in preempted:
        names = [e[0] for e in rec["events"]]
        assert names.count("admit") >= 2, names
        assert names.index("admit") < names.index("preempt") < (
            len(names) - 1 - names[::-1].index("admit"))
        assert rec["trace_id"]  # one id spans both hops


def test_oversubscribed_sequential_load_never_wedges(lm, rng):
    """Many queued requests against a pool that fits ~one at a time:
    admission parks on the dry pool, unparks as slots free, and every
    request completes correctly (no deadlock, no starvation)."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=2, max_queue=32,
                           kv_pool_blocks=7, kv_block_tokens=4)
    prompts = [_prompt(rng, 9) for _ in range(6)]

    async def work():
        reqs = [engine.submit(p, 6) for p in prompts]
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(engine, work()))
    assert outs == [_want(lm, p, 6) for p in prompts]


# -- long-context admission + typed OOM --------------------------------------

def test_paged_serves_context_beyond_dense_padded_max(lm, rng):
    """The capacity headline in miniature: at the SAME byte budget a
    dense engine must shrink its padded per-slot max (max_context) to
    afford its slots, rejecting longer requests up front — the paged
    engine chains blocks on demand and serves the same request to
    completion, token-identically."""
    model, variables = lm
    # Dense at this budget: 2 slots x 16-position rows. 8 blocks x 4
    # tokens is the same 32 positions' worth of KV bytes.
    dense = ServingEngine(model, variables, slots=2, max_context=16)
    paged = ServingEngine(model, variables, slots=2,
                          kv_pool_blocks=8, kv_block_tokens=4)
    long_prompt = _prompt(rng, 20)  # + 6 new = 26 > dense's padded 16

    with pytest.raises(ValueError, match="context cap"):
        dense.submit(long_prompt, 6)

    async def drive():
        return await paged.submit(long_prompt, 6).result()

    got = asyncio.run(_run_engine(paged, drive()))
    assert got == _want(lm, long_prompt, 6)


def test_paged_prefill_bucket_never_overshoots_trained_context(lm, rng):
    """Regression: with a block size that does NOT divide the context
    (table reach rounds UP past max_seq_len) a prefix hit near the
    trained limit used to let the tail chunk's pad width overshoot the
    positional table — the positional dynamic_slice then clamps
    BACKWARD and embeds the chunk's real tokens at wrong positions.
    The pad-width bound must be the context limit, not the table
    reach."""
    # seq 64 == the positional table's full length (no slack), and 12
    # does not divide it: the table reach rounds up to 72 > 64.
    model = gpt_tiny(seq_len=64, vocab_size=VOCAB)
    variables = model.init(0)
    engine = ServingEngine(model, variables, slots=1, max_queue=8,
                           kv_pool_blocks=16, kv_block_tokens=12)
    prompt = _prompt(rng, 61)  # + 3 new = the full trained context

    async def drive():
        outs = []
        for _ in range(2):  # second run hits 60 cached tokens: the
            # tail chunk prefills 1 token at pos 60, padded past it
            outs.append(await engine.submit(prompt, 3).result())
        return outs

    outs = asyncio.run(_run_engine(engine, drive()))
    want = generate(model, variables, np.asarray([prompt], np.int32), 3,
                    greedy=True)[0].tolist()
    assert outs == [want, want], "positional clamp corrupted the hit"
    assert engine.kv_pool.stats()["hit_tokens"] >= 60


def test_pool_exhausted_is_typed_and_counted(lm, rng):
    """A request whose full context can NEVER fit the pool is a sizing
    error: typed ``kv_oom`` reject at submit, before any device work,
    with the counter bumped — unlike transient pressure, which queues."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1,
                           kv_pool_blocks=3, kv_block_tokens=4)
    with pytest.raises(PoolExhausted) as ei:
        engine.submit(_prompt(rng, 10), 8)  # 17 resident > 12 poolable
    assert ei.value.code == "kv_oom"
    assert engine.metrics.oom_rejections == 1


# -- observability ------------------------------------------------------------

def test_pool_gauges_counters_and_debugz_block_depth(lm, rng):
    """Satellite: kv_pool_blocks_{total,used,free} gauges and the
    preemption/oom counters publish to the registry, and the debugz slot
    table carries per-slot block-table depth while a request decodes."""
    model, variables = lm
    metrics = ServingMetrics()
    engine = ServingEngine(model, variables, slots=2, max_queue=8,
                           kv_pool_blocks=16, kv_block_tokens=4,
                           metrics=metrics)
    seen = {}

    async def drive():
        req = engine.submit(_prompt(rng, 9), 8)
        async for _ in req.tokens():
            if "dz" not in seen:
                seen["dz"] = engine.debugz()
        return req

    asyncio.run(_run_engine(engine, drive()))
    snap = metrics.registry.snapshot()
    assert snap["kv_pool_blocks_total"]["value"] == 16
    assert (snap["kv_pool_blocks_used"]["value"]
            + snap["kv_pool_blocks_free"]["value"]) == 16
    assert snap["kv_preemptions_total"]["kind"] == "counter"
    assert snap["kv_oom_rejections_total"]["kind"] == "counter"
    # Mid-decode debugz: the busy slot reported its block-table depth.
    busy = [s for s in seen["dz"]["slots"] if s["state"] != "free"]
    assert busy and busy[0]["blocks"] >= 3  # 9 prompt tokens -> >= 3 blocks
    assert "shared_blocks" in busy[0]
    kp = seen["dz"]["kv_pool"]
    assert kp["capacity_blocks"] == 16 and kp["blocks_free"] < 16
