"""MoE expert-parallel tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.moe import MoEMLP
from distkeras_tpu.parallel.mesh import make_mesh


def _build(rng, E=4, D=16, M=32, factor=8.0, top_k=1):
    module = MoEMLP(num_experts=E, mlp_dim=M, capacity_factor=factor,
                    dtype=jnp.float32, router_top_k=top_k)
    x = jnp.asarray(rng.normal(size=(2, 8, D)), jnp.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    import flax.linen as nn

    return module, nn.meta.unbox(dict(variables)), x


def test_moe_matches_per_token_reference(rng):
    # capacity_factor large enough that nothing is dropped
    module, variables, x = _build(rng)
    out = module.apply(variables, x)
    ref = MoEMLP.reference_forward(variables, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_moe_top2_matches_per_token_reference(rng):
    # ample capacity: the dispatch-tensor top-2 equals the per-token gather
    module, variables, x = _build(rng, top_k=2)
    out = module.apply(variables, x)
    ref = MoEMLP.reference_forward(variables, x, top_k=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)
    # top-2 output differs from top-1 (second expert contributes)
    ref1 = MoEMLP.reference_forward(variables, x, top_k=1)
    assert np.abs(np.asarray(ref) - np.asarray(ref1)).max() > 1e-5


def test_moe_top2_second_choices_dropped_first(rng):
    # Tight capacity: every expert keeps its first-choice tokens before any
    # second choice seats. With capacity == count of first choices for the
    # busiest expert, that expert serves no second choices.
    module, variables, x = _build(rng, top_k=2, factor=0.5)
    out = module.apply(variables, x)
    assert np.isfinite(np.asarray(out)).all()
    # some tokens lose their second expert -> output differs from the
    # uncapped reference, but no token is fully dropped into NaN
    ref = MoEMLP.reference_forward(variables, x, top_k=2)
    assert np.abs(np.asarray(out) - np.asarray(ref)).max() > 1e-6


def test_moe_top2_gradients_flow(rng):
    module, variables, x = _build(rng, top_k=2)

    def loss(v):
        return jnp.mean(module.apply(v, x) ** 2)

    g = jax.grad(loss)(variables)
    for leaf in ("w_in", "w_out", "router"):
        gn = np.asarray(jnp.linalg.norm(g["params"][leaf].reshape(-1)))
        assert np.isfinite(gn) and gn > 0, leaf


@pytest.mark.slow
def test_moe_top2_bert_trains_on_ep_mesh(rng):
    """Top-2 MoE-BERT end-to-end on a dp x ep mesh; aux loss decreases
    (VERDICT r1 item 9)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import bert_tiny_moe_mlm

    vocab, seq = 64, 8
    tokens = np.asarray(rng.integers(1, vocab, size=(128, seq)), np.int32)
    ds = dk.Dataset.from_arrays(features=tokens, label=tokens)
    mesh = make_mesh({"dp": 2, "ep": 4})
    model = bert_tiny_moe_mlm(seq_len=seq, vocab_size=vocab, num_experts=4,
                              top_k=2)

    # Track the sown aux loss across training via the step engine's metrics:
    # recompute it on a fixed probe batch before and after training.
    probe = jnp.asarray(tokens[:16])

    def aux_of(variables):
        _, state = model.apply(
            variables, probe, train=True, rngs={"dropout": jax.random.PRNGKey(0)}
        )
        return float(sum(np.sum(np.asarray(l)) for l in jax.tree.leaves(state["aux_loss"])))

    trainer = dk.SynchronousDistributedTrainer(
        model, worker_optimizer="adam", learning_rate=1e-3,
        batch_size=8, num_epoch=3, mesh=mesh, aux_loss_weight=0.05,
    )
    aux_before = aux_of(model.init(trainer.seed))
    trained = trainer.train(ds)
    hist = trainer.get_history()
    assert hist[-1]["loss"] < hist[0]["loss"]
    aux_after = aux_of(jax.device_get(trained.variables))
    assert aux_after < aux_before * 1.05  # balanced or improving routing


def test_moe_capacity_drops_pass_through(rng):
    # capacity 1 slot per expert: overflowing tokens keep their residual
    module, variables, x = _build(rng, factor=0.0001)
    out = module.apply(variables, x)
    assert np.isfinite(np.asarray(out)).all()
    # dropped tokens equal input exactly (residual passthrough)
    diff = np.abs(np.asarray(out) - np.asarray(x)).reshape(-1, x.shape[-1]).sum(-1)
    assert (diff < 1e-6).sum() > 0  # at least some tokens dropped


def test_moe_expert_sharded_over_ep(rng):
    from distkeras_tpu.parallel.sharding import infer_variable_shardings

    module = MoEMLP(num_experts=8, mlp_dim=16, dtype=jnp.float32)
    x = jnp.zeros((2, 4, 16), jnp.float32)
    mesh = make_mesh({"dp": 2, "ep": 4})
    abstract = jax.eval_shape(
        lambda r: dict(module.init(r, x)), jax.random.PRNGKey(0)
    )
    shardings = infer_variable_shardings(mesh, abstract)
    import flax.linen as nn

    variables = jax.jit(
        lambda r: nn.meta.unbox(dict(module.init(r, x))), out_shardings=shardings
    )(jax.random.PRNGKey(0))
    w_in = variables["params"]["w_in"]
    # [E=8, D=16, M=16] sharded over ep=4 -> 2 experts per device
    assert {s.data.shape for s in w_in.addressable_shards} == {(2, 16, 16)}
    # forward under jit with sharded experts runs and is finite
    out = jax.jit(module.apply)(variables, x)
    assert np.isfinite(np.asarray(out)).all()


def test_moe_gradients_flow(rng):
    module, variables, x = _build(rng)

    def loss(v):
        return jnp.mean(module.apply(v, x) ** 2)

    g = jax.grad(loss)(variables)
    gn = np.asarray(jnp.linalg.norm(g["params"]["w_in"].reshape(-1)))
    assert np.isfinite(gn) and gn > 0


@pytest.mark.slow
def test_moe_bert_trains_on_ep_mesh(rng):
    """MoE-BERT end-to-end on a dp x ep mesh via the sync trainer."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import bert_tiny_moe_mlm

    vocab, seq = 64, 8
    tokens = np.asarray(rng.integers(1, vocab, size=(128, seq)), np.int32)
    ds = dk.Dataset.from_arrays(features=tokens, label=tokens)
    mesh = make_mesh({"dp": 2, "ep": 4})
    trainer = dk.SynchronousDistributedTrainer(
        bert_tiny_moe_mlm(seq_len=seq, vocab_size=vocab, num_experts=4),
        worker_optimizer="adam", learning_rate=1e-3,
        batch_size=8, num_epoch=3, mesh=mesh,
    )
    trainer.train(ds)
    hist = trainer.get_history()
    assert hist[-1]["loss"] < hist[0]["loss"]


@pytest.mark.slow
def test_moe_aux_loss_sown_and_added(rng):
    """The load-balance aux loss is sown during train-apply and joins the
    training objective via the step engine."""
    import jax.numpy as jnp
    from distkeras_tpu.models.bert import bert_tiny_moe_mlm
    from distkeras_tpu.ops.losses import get_optimizer
    from distkeras_tpu.training.step import TrainState, make_train_step

    model = bert_tiny_moe_mlm(seq_len=8, vocab_size=64, num_experts=4)
    # aux collection sown during train apply
    variables = model.init(0)
    assert "aux_loss" not in variables
    out, state = model.apply(variables, jnp.zeros((2, 8), jnp.int32), train=True,
                             rngs={"dropout": jax.random.PRNGKey(0)})
    assert "aux_loss" in state
    aux_leaves = jax.tree.leaves(state["aux_loss"])
    assert aux_leaves and all(np.isfinite(np.asarray(l)).all() for l in aux_leaves)
    # load balance term is >= 1 (equals 1 at perfectly uniform routing)
    assert float(sum(np.sum(l) for l in aux_leaves)) >= 2.0 * 0.99  # 2 layers

    # step engine: aux-weighted loss > task loss with weight 0, same metrics
    opt = get_optimizer("sgd", 0.0)
    tokens = np.asarray(rng.integers(0, 64, size=(4, 8)), np.int32)
    batch = {"features": tokens, "label": tokens}
    s = TrainState.create(model, opt, rng=0)
    step0 = make_train_step(model, opt, "categorical_crossentropy", metrics=(),
                            donate=False, aux_loss_weight=0.0)
    step1 = make_train_step(model, opt, "categorical_crossentropy", metrics=(),
                            donate=False, aux_loss_weight=0.5)
    _, m0 = step0(s, batch)
    _, m1 = step1(s, batch)
    assert float(m1["loss"]) > float(m0["loss"])
    # aux_loss never leaks into carried model state
    s1, _ = step1(s, batch)
    assert "aux_loss" not in s1.model_state
