"""Golden fixed-seed regression tests (SURVEY §4 strategy): the exact
numbers a known seed must reproduce. Loose-enough tolerances to survive
XLA version drift, tight enough to catch semantic regressions (changed rng
threading, shuffling, optimizer wiring)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # integration-scale; run with `pytest -m ''`

import distkeras_tpu as dk
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP


@pytest.fixture
def golden_problem():
    rng = np.random.default_rng(1234)
    x = rng.normal(size=(512, 20)).astype(np.float32)
    w = rng.normal(size=(20,))
    y = (x @ w > 0).astype(np.float32)
    return dk.Dataset.from_arrays(features=x, label=y)


def _model():
    return Model.from_flax(MLP(features=(32,), num_classes=2), input_shape=(20,))


def test_golden_single_trainer(golden_problem):
    t = dk.SingleTrainer(_model(), worker_optimizer="adam", learning_rate=0.01,
                         batch_size=32, num_epoch=5, seed=7)
    trained = t.train(golden_problem, shuffle=True)
    hist = t.get_history()
    # recorded 2026-07-29 (jax 0.9.0, CPU): loss 0.0438593, acc 1.0.
    # 1% relative tolerance (tightened from 5% after two rounds of stable
    # seeds — VERDICT r3 task 7): survives XLA fusion-order drift, catches
    # any semantic change (rng threading, shuffle order, optimizer wiring).
    assert hist[-1]["loss"] == pytest.approx(0.0438593, rel=0.01)
    assert hist[-1]["accuracy"] >= 0.99
    m = t.evaluate(trained, golden_problem)
    assert m["accuracy"] == pytest.approx(0.998047, abs=0.004)
    assert m["loss"] == pytest.approx(0.0506882, rel=0.01)


def test_golden_deterministic_across_runs(golden_problem):
    def run():
        t = dk.SingleTrainer(_model(), worker_optimizer="adam",
                             learning_rate=0.01, batch_size=32, num_epoch=2,
                             seed=7)
        t.train(golden_problem, shuffle=True)
        return t.get_history()[-1]["loss"]

    assert run() == run()  # bit-identical


def test_golden_sync_trainer(golden_problem):
    """Sync (GSPMD dp) family pin."""
    t = dk.SynchronousDistributedTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        num_workers=4, batch_size=32, num_epoch=5, seed=7,
    )
    t.train(golden_problem, shuffle=True)
    hist = t.get_history()
    # recorded 2026-07-29 (jax 0.9.0, 8-device CPU mesh)
    assert hist[-1]["loss"] == pytest.approx(0.1608761, rel=0.01)


def test_golden_adag_trainer(golden_problem):
    """Async/ADAG family pin: one worker makes the window/exchange cadence
    deterministic (single PS committer; the rebase point in the drive loop
    is fixed), so the protocol math + PS scaffold pin to 1%."""
    t = dk.ADAG(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        num_workers=1, batch_size=32, num_epoch=5, seed=7,
        communication_window=4,
    )
    t.train(golden_problem, shuffle=True)
    hist = t.get_history()
    # recorded 2026-07-29 (jax 0.9.0, 8-device CPU mesh)
    assert hist[-1]["loss"] == pytest.approx(0.1025242, rel=0.01)


def test_golden_pipeline_trainer():
    """Pipeline family pin: pp=2 BERT copy task, fixed seed, no dropout."""
    from distkeras_tpu.models.bert import BertConfig, _make

    rng = np.random.default_rng(1234)
    x = rng.integers(0, 32, size=(64, 8)).astype(np.int32)
    ds = dk.Dataset.from_arrays(features=x, label=x.copy())
    cfg = BertConfig(vocab_size=32, hidden_size=16, num_layers=2,
                     num_heads=2, mlp_dim=32, max_seq_len=8,
                     dropout_rate=0.0)
    t = dk.PipelineTrainer(
        _make(cfg, 8, "golden_pipe"), worker_optimizer="adam",
        learning_rate=3e-3, num_stages=2, num_microbatches=2,
        batch_size=16, num_epoch=3, seed=7,
    )
    t.train(ds, shuffle=True)
    hist = t.get_history()
    # recorded 2026-07-30, dropout pinned off (jax 0.9.0, 8-dev CPU mesh)
    assert hist[-1]["loss"] == pytest.approx(3.2230043, rel=0.01)


def test_golden_pipeline_1f1b_matches_gpipe_pin():
    """1F1B family pin: the hand-rolled backward must keep reproducing the
    gpipe golden trajectory (same model/data/seed as the pipeline pin)."""
    from distkeras_tpu.models.bert import BertConfig, _make

    rng = np.random.default_rng(1234)
    x = rng.integers(0, 32, size=(64, 8)).astype(np.int32)
    ds = dk.Dataset.from_arrays(features=x, label=x.copy())
    cfg = BertConfig(vocab_size=32, hidden_size=16, num_layers=2,
                     num_heads=2, mlp_dim=32, max_seq_len=8,
                     dropout_rate=0.0)
    t = dk.PipelineTrainer(
        _make(cfg, 8, "golden_1f1b"), worker_optimizer="adam",
        learning_rate=3e-3, num_stages=2, num_microbatches=2,
        batch_size=16, num_epoch=3, seed=7, schedule="1f1b",
    )
    t.train(ds, shuffle=True)
    hist = t.get_history()
    # recorded 2026-07-30, dropout pinned off (jax 0.9.0, 8-dev CPU mesh):
    # 3.2233820 vs the gpipe pin 3.2230043 — identical math through a
    # different schedule, 0.012% apart (bf16-free f32 reduction-order
    # effects only)
    assert hist[-1]["loss"] == pytest.approx(3.2233820, rel=0.01)
