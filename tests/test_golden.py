"""Golden fixed-seed regression tests (SURVEY §4 strategy): the exact
numbers a known seed must reproduce. Loose-enough tolerances to survive
XLA version drift, tight enough to catch semantic regressions (changed rng
threading, shuffling, optimizer wiring)."""

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP


@pytest.fixture
def golden_problem():
    rng = np.random.default_rng(1234)
    x = rng.normal(size=(512, 20)).astype(np.float32)
    w = rng.normal(size=(20,))
    y = (x @ w > 0).astype(np.float32)
    return dk.Dataset.from_arrays(features=x, label=y)


def _model():
    return Model.from_flax(MLP(features=(32,), num_classes=2), input_shape=(20,))


def test_golden_single_trainer(golden_problem):
    t = dk.SingleTrainer(_model(), worker_optimizer="adam", learning_rate=0.01,
                         batch_size=32, num_epoch=5, seed=7)
    trained = t.train(golden_problem, shuffle=True)
    hist = t.get_history()
    # recorded 2026-07-29 (jax 0.9.0, CPU): loss 0.0438593, acc 1.0.
    # ~5% relative tolerance: survives XLA fusion-order drift across
    # versions, catches any semantic change (rng threading, shuffle order,
    # optimizer wiring) — those shift the loss by far more.
    assert hist[-1]["loss"] == pytest.approx(0.0438593, rel=0.05)
    assert hist[-1]["accuracy"] >= 0.99
    m = t.evaluate(trained, golden_problem)
    assert m["accuracy"] == pytest.approx(0.998047, abs=0.004)
    assert m["loss"] == pytest.approx(0.0506882, rel=0.05)


def test_golden_deterministic_across_runs(golden_problem):
    def run():
        t = dk.SingleTrainer(_model(), worker_optimizer="adam",
                             learning_rate=0.01, batch_size=32, num_epoch=2,
                             seed=7)
        t.train(golden_problem, shuffle=True)
        return t.get_history()[-1]["loss"]

    assert run() == run()  # bit-identical
