"""Weight-version provenance: checkpoint digest -> served request.

The contract under test: every weights file is stamped with a monotonic
version + content digest; the serving engine carries that stamp into
every done line, timeline record, healthz, metricsz, and debugz; and a
reload moves the stamp atomically with the params — so any served
answer traces to the exact checkpoint that produced it, with the armed
``RecompileAuditor`` proving the provenance plumbing costs zero
retraces.
"""

import asyncio

import numpy as np
import pytest

from distkeras_tpu.checkpoint import (
    load_weights_file,
    load_weights_file_with_provenance,
    load_weights_meta,
    save_weights_file,
    weights_digest,
    weights_provenance,
)
from distkeras_tpu.models.bert import gpt_tiny
from distkeras_tpu.serving import (
    ServingClient,
    ServingEngine,
    ServingServer,
)
from distkeras_tpu.telemetry import RecompileAuditor, TraceStore
from distkeras_tpu.utils.pytree import pytree_to_host, serialize_pytree

VOCAB = 64


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(0)


def _tree(seed):
    rng = np.random.default_rng(seed)
    return {"params": {"w": rng.normal(size=(4, 3)).astype(np.float32)}}


# -- stamping unit behavior ---------------------------------------------------

def test_save_weights_file_stamps_monotonic_version_and_digest(tmp_path):
    path = str(tmp_path / "w.npz")
    save_weights_file(path, _tree(0))
    m1 = load_weights_meta(path)
    assert m1["version"] == 1 and len(m1["digest"]) == 16
    assert m1["saved_at"] > 0

    # Same content re-published at the same path: version moves, digest
    # does not — "what changed" and "did it change" are separate facts.
    save_weights_file(path, _tree(0))
    m2 = load_weights_meta(path)
    assert m2["version"] == 2 and m2["digest"] == m1["digest"]

    # Different content -> different digest.
    save_weights_file(path, _tree(1))
    m3 = load_weights_meta(path)
    assert m3["version"] == 3 and m3["digest"] != m1["digest"]

    # The stamp never breaks array loading (extra zip member is ignored
    # by the npz readers), and the one-read loader agrees with the
    # stamp.
    tree = load_weights_file(path)
    assert np.allclose(tree["params"]["w"], _tree(1)["params"]["w"])
    loaded, prov = load_weights_file_with_provenance(path)
    assert prov["version"] == 3 and prov["digest"] == m3["digest"]
    assert np.allclose(loaded["params"]["w"], _tree(1)["params"]["w"])


def test_legacy_unstamped_file_gets_the_same_digest(tmp_path):
    """A pre-stamping file IS the bare serialized pytree, so computing
    the digest over its bytes equals what the stamper would have
    recorded for the same content."""
    tree = _tree(2)
    data = serialize_pytree(pytree_to_host(tree))
    legacy = tmp_path / "legacy.npz"
    legacy.write_bytes(data)
    assert load_weights_meta(str(legacy)) is None
    prov = weights_provenance(str(legacy))
    assert prov["version"] == 0
    assert prov["digest"] == weights_digest(data)

    stamped = str(tmp_path / "stamped.npz")
    save_weights_file(stamped, tree)
    assert load_weights_meta(stamped)["digest"] == prov["digest"]


def test_explicit_version_and_meta_ride_the_stamp(tmp_path):
    path = str(tmp_path / "w.npz")
    save_weights_file(path, _tree(0), version=41, meta={"step": 1000})
    m = load_weights_meta(path)
    assert m["version"] == 41 and m["step"] == 1000
    save_weights_file(path, _tree(0))  # monotonic from the stamp
    assert load_weights_meta(path)["version"] == 42


def test_trained_model_save_weights_is_stamped(tmp_path):
    from distkeras_tpu.models.core import TrainedModel

    path = str(tmp_path / "trained.npz")
    TrainedModel(None, _tree(3)).save_weights(path)
    assert load_weights_meta(path)["version"] == 1


# -- end-to-end: train-shaped weights file -> served request ------------------

def test_served_requests_carry_checkpoint_provenance_across_reload(
        lm, rng, tmp_path):
    """Serve a stamped weights file, stream a request, reload a NEW
    file, stream again: each done line and tracez timeline carries the
    version+digest of the checkpoint that served IT (old vs new visible
    per request), healthz/debugz/metricsz agree, and the armed auditor
    proves the whole provenance layer never touched the compiled decode
    step (compile-count == 1)."""
    model, variables = lm
    path_v1 = str(tmp_path / "weights.npz")
    save_weights_file(path_v1, variables)
    prov_v1 = weights_provenance(path_v1)
    assert prov_v1["version"] == 1 and prov_v1["digest"]

    # "Newly trained" weights published to the same path: version 2.
    save_weights_file(path_v1, model.init(1))
    prov_v2 = weights_provenance(path_v1)
    assert prov_v2["version"] == 2
    assert prov_v2["digest"] != prov_v1["digest"]
    # Roll BACK the file so the server boots on v1, then re-publish v2
    # during the test.
    save_weights_file(path_v1, variables, version=1)
    assert weights_provenance(path_v1)["digest"] == prov_v1["digest"]

    prompt = rng.integers(0, VOCAB, size=(5,)).tolist()

    async def go():
        v1_vars, v1_prov = load_weights_file_with_provenance(
            path_v1, like=variables)
        store = TraceStore(16)
        auditor = RecompileAuditor()
        engine = ServingEngine(
            model, v1_vars, slots=2, max_queue=8,
            weight_version=v1_prov, trace_store=store,
            auditor=auditor, arm_auditor_after_warmup=True)
        server = ServingServer(engine, port=0)
        await server.start()
        async with ServingClient("127.0.0.1", server.port) as c:
            done1 = await c.generate(prompt, 4, trace_id="prov-one")
            health1 = await c.healthz()
            # Publish v2 and roll the replica onto it.
            save_weights_file(path_v1, model.init(1), version=2)
            reload_rep = await c.reload(path_v1, timeout=30.0)
            done2 = await c.generate(prompt, 4, trace_id="prov-two")
            health2 = await c.healthz()
            snap = await c.metricsz()
            dz = await c.debugz()
            tz1 = await c.tracez("prov-one")
            tz2 = await c.tracez("prov-two")
        await server.stop(drain=True)
        compiles = engine.decode_compile_count()
        return (done1, done2, health1, health2, reload_rep, snap, dz,
                tz1, tz2, compiles)

    (done1, done2, health1, health2, reload_rep, snap, dz,
     tz1, tz2, compiles) = asyncio.run(go())

    # Done lines: each request names the checkpoint that served it —
    # version + digest ONLY (the server-side file path must not leak
    # to remote clients).
    assert set(done1["weight_version"]) == {"version", "digest"}
    assert done1["weight_version"]["version"] == 1
    assert done1["weight_version"]["digest"] == prov_v1["digest"]
    assert done2["weight_version"]["version"] == 2
    assert done2["weight_version"]["digest"] == prov_v2["digest"]

    # Trace timelines agree with the done lines — old vs new across the
    # reload, queryable post-hoc by trace id.
    wv1 = tz1["hops"][0]["data"]["weight_version"]
    wv2 = tz2["hops"][0]["data"]["weight_version"]
    assert wv1["digest"] == prov_v1["digest"]
    assert wv2["digest"] == prov_v2["digest"]

    # healthz before/after, the reload's own reply, debugz, metricsz.
    assert health1["weight_version"]["digest"] == prov_v1["digest"]
    assert health2["weight_version"]["digest"] == prov_v2["digest"]
    assert reload_rep["weight_version"]["digest"] == prov_v2["digest"]
    assert dz["weight_version"]["version"] == 2
    assert snap["serving_weight_version"]["value"] == 2
    live = f'serving_weight_info{{digest={prov_v2["digest"]},version=2}}'
    old = f'serving_weight_info{{digest={prov_v1["digest"]},version=1}}'
    assert snap[live]["value"] == 1
    assert snap[old]["value"] == 0  # superseded info series zeroed

    # Device-memory accounting rides healthz with the typed sentinel.
    assert health2["device_memory"], "healthz lost device_memory"
    for m in health2["device_memory"]:
        if not m["available"]:
            assert m["bytes_in_use"] is None
    assert snap["model_params_bytes"]["value"] > 0

    # The provenance layer is host-only: ONE decode executable across
    # stream -> reload -> stream, with the auditor armed throughout.
    assert compiles == 1


def test_param_swap_waits_for_streamed_queued_resume(lm, rng):
    """A preempted-and-requeued request (streamed tokens, queued, zero
    active slots) must finish under the weights that produced its
    streamed prefix: a pending swap holds until the queue carries no
    streamed request, and the resume's done provenance is the OLD
    stamp while post-swap requests carry the new one. The swap request
    lands BEFORE the run loop's first iteration — without the gate it
    would execute ahead of the resume's re-admission."""
    model, variables = lm
    prompt = rng.integers(0, VOCAB, size=(6,)).tolist()
    old = {"version": 5, "digest": "aaa"}
    new = {"version": 6, "digest": "bbb"}

    async def go():
        engine = ServingEngine(model, variables, slots=1, max_queue=4,
                               kv_pool_blocks=16, kv_block_tokens=4,
                               weight_version=old)
        req = engine.submit(prompt, 6)
        req.out_tokens.extend([1, 2])  # a mid-stream preempted resume
        event, result = engine.request_param_swap(variables, provenance=new)
        task = asyncio.create_task(engine.run())
        await req.result()
        await asyncio.wait_for(event.wait(), 30)
        req2 = engine.submit(prompt, 2)
        await req2.result()
        engine.shutdown(drain=True)
        await task
        return (req.weight_version, result, dict(engine.weight_version),
                req2.weight_version)

    wv1, result, wv_after, wv2 = asyncio.run(go())
    assert wv1 == old, "resume was restamped across the swap"
    assert result.get("ok") is True
    assert wv_after == new and wv2 == new


def test_engine_inline_swap_bumps_version_without_digest(lm):
    """Direct request_param_swap with no file: the version still moves
    (mixed-fleet detection keeps working) with digest None."""
    model, variables = lm

    async def go():
        engine = ServingEngine(model, variables, slots=1, max_queue=4)
        task = asyncio.create_task(engine.run())
        event, result = engine.request_param_swap(variables)
        await asyncio.wait_for(event.wait(), 30)
        engine.shutdown(drain=True)
        await task
        return result, engine.weight_version

    result, wv = asyncio.run(go())
    assert result.get("ok") is True
    assert wv == {"version": 1, "digest": None}
