"""gRPC PS transport tests: same protocol semantics across the wire."""

import threading

import numpy as np
import pytest

from distkeras_tpu.parallel.protocols import ADAGProtocol, DynSGDProtocol
from distkeras_tpu.parallel.ps_grpc import (
    GrpcClient,
    GrpcParameterServer,
    determine_host_address,
)


@pytest.fixture
def adag_server():
    ps = GrpcParameterServer(
        ADAGProtocol(), {"w": np.zeros(4, np.float32)}, num_workers=2, port=0
    )
    port = ps.start()
    yield ps, port
    ps.stop()


def test_determine_host_address():
    addr = determine_host_address()
    assert isinstance(addr, str) and addr.count(".") == 3


def test_pull_commit_over_wire(adag_server):
    ps, port = adag_server
    client = GrpcClient("127.0.0.1", port)
    center, n = client.pull()
    assert np.allclose(center["w"], 0.0) and n == 0
    client.commit({"delta": {"w": np.full(4, 8.0, np.float32)}})
    center, n = client.pull()
    # ADAG normalization: 8 / num_workers(2) = 4
    assert np.allclose(center["w"], 4.0)
    assert n == 1
    client.close()


def test_dynsgd_counter_over_wire():
    ps = GrpcParameterServer(
        DynSGDProtocol(), {"w": np.zeros(2, np.float32)}, num_workers=2, port=0
    )
    port = ps.start()
    try:
        c = GrpcClient("127.0.0.1", port)
        _, last = c.pull()
        c.commit({"delta": {"w": np.ones(2, np.float32)}, "last_update": last})
        center, n = c.pull()
        assert n == 1
        assert np.allclose(center["w"], 1.0)  # staleness 0 -> full delta
        # stale commit: server at 1, last_update 0 -> delta/2
        c.commit({"delta": {"w": np.ones(2, np.float32)}, "last_update": 0})
        center, n = c.pull()
        assert np.allclose(center["w"], 1.5)
        c.close()
    finally:
        ps.stop()


def test_concurrent_grpc_clients(adag_server):
    ps, port = adag_server

    def worker():
        c = GrpcClient("127.0.0.1", port)
        for _ in range(25):
            c.commit({"delta": {"w": np.ones(4, np.float32)}})
        c.pull()
        c.close()

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ps.service.num_commits == 100
    # ADAG: each delta scaled by 1/2 -> 100 * 1 / 2 = 50
    final = ps.get_model()
    assert np.allclose(final["w"], 50.0)


def test_nested_pytree_over_wire(adag_server):
    ps, port = adag_server
    # structural deserialization (no `like`) must rebuild nested dicts
    client = GrpcClient("127.0.0.1", port)
    center, _ = client.pull()
    assert set(center.keys()) == {"w"}
    client.close()


def test_async_trainer_over_grpc_transport(toy_classification=None):
    """Full DOWNPOUR run with the PS behind gRPC (DCN-path e2e)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.core import Model
    from distkeras_tpu.models.mlp import MLP

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    model = Model.from_flax(MLP(features=(16,), num_classes=2), input_shape=(8,))
    trainer = dk.DOWNPOUR(
        model, worker_optimizer="adam", learning_rate=0.01,
        num_workers=2, batch_size=16, num_epoch=4, communication_window=4,
        transport="grpc",
    )
    trained = trainer.train(ds)
    assert trainer.parameter_server.num_commits > 0
    preds = trained.predict(x)
    acc = float(np.mean((np.argmax(preds, -1) == y)))
    assert acc > 0.85, acc


def test_grpc_health_rpc(adag_server):
    ps, port = adag_server
    client = GrpcClient("127.0.0.1", port)
    h = client.health()
    assert h["running"] is True and h["num_commits"] == 0
    client.commit({"delta": {"w": np.ones(4, np.float32)}})
    client.pull()
    assert client.health()["num_commits"] == 1
    client.close()


def test_elastic_fused_wire_bytes_drop_2x():
    """VERDICT r3 task 8: the AEASGD fused exchange must cost ≤ half the
    raw-f32 wire bytes per steady-state window, measured on real encoded
    gRPC frames, with semantics preserved (force computed against the PS's
    own center — covered by the protocol tests)."""
    from distkeras_tpu.parallel.protocols import AEASGDProtocol
    from distkeras_tpu.parallel import ps_grpc

    n_params = 32768
    center = {"w": np.zeros(n_params, np.float32),
              "b": np.zeros(512, np.float32)}
    proto = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    ps = GrpcParameterServer(proto, center, num_workers=1, port=0)
    port = ps.start()
    try:
        client = GrpcClient("127.0.0.1", port, like=center)
        up_bytes, down_bytes = [], []
        orig = client._commit_pull

        def recording(req, timeout=None):
            up_bytes.append(len(req))
            rep = orig(req, timeout=timeout)
            down_bytes.append(len(rep))
            return rep

        client._commit_pull = recording

        rng = np.random.default_rng(0)
        params, carry = proto.worker_begin(client, None)
        for _ in range(3):
            params = {k: v + 1e-3 * rng.normal(size=v.shape).astype(np.float32)
                      for k, v in params.items()}
            params, carry = proto.worker_window(params, carry, client)
        client.close()

        # Baseline: what one window cost before — full f32 local up, full
        # f32 force down (same tree both ways).
        raw_up = len(ps_grpc._encode_commit(
            {"local": params, "worker_id": carry.worker_id, "last_update": 0}
        ))
        raw_down = len(ps_grpc._encode_pull_reply(params, 0))
        raw_round_trip = raw_up + raw_down

        # Window 1 bootstraps at full precision; windows 2+ are steady state.
        steady = up_bytes[-1] + down_bytes[-1]
        assert up_bytes[0] + down_bytes[0] >= raw_round_trip * 0.9  # bootstrap
        assert steady * 2 <= raw_round_trip * 1.05, (
            f"steady-state window {steady}B vs raw {raw_round_trip}B — "
            "expected ≥2× drop (modulo npz framing overhead)"
        )
    finally:
        ps.stop()
