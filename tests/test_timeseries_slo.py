"""Fleet telemetry plane: mergeable histograms, windowed timeseries,
delta encoding, the router-side fold, and the SLO burn-rate engine.

The contracts under test, all host-side (no jax):

- histogram merge is a proper commutative monoid on ``state()`` dicts
  (commutative, associative) and BUCKET-EXACT: merging per-replica
  states equals the state of one histogram that saw every sample, so a
  fleet percentile computed from the merge equals the single-registry
  ground truth — not an estimate over estimates;
- ``DeltaEncoder`` ships only what changed (bucket-count diffs, counter
  increments), re-ships full state on a reset source, and a quiet
  registry's delta is empty;
- ``TimeSeriesStore`` rolls fixed-width windows on an injected clock,
  skips quiet gaps without minting empty windows, bounds memory at
  ``capacity``, and ``summary()`` over any span is the bucket-exact
  merge of its windows;
- ``FleetAggregator`` folds pushes into per-replica + fleet="all"
  series; the windowed fleet percentile matches an offline recompute
  over the pooled raw samples; ``forget_replica`` drops ONLY the dead
  replica's gauges (its counted history stays);
- ``SLOEngine``: objective validation is loud, latency thresholds snap
  to bucket bounds, the multiwindow rule pages only when BOTH windows
  burn, the ok -> warn -> page -> ok state machine records transition
  events with exemplar trace ids harvested from the offending buckets.
"""

import pytest

from distkeras_tpu.serving.slo import Objective, SLOEngine
from distkeras_tpu.telemetry import MetricsRegistry
from distkeras_tpu.telemetry.registry import (
    hist_state_percentile,
    merge_hist_states,
)
from distkeras_tpu.telemetry.timeseries import (
    DeltaEncoder,
    FleetAggregator,
    TimeSeriesStore,
)

BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _hist_state(values, exemplars=None, buckets=BUCKETS):
    """A ``state()`` dict from a fresh histogram fed ``values``."""
    reg = MetricsRegistry()
    h = reg.histogram("m", buckets=buckets)
    for i, v in enumerate(values):
        h.observe(v, exemplar=exemplars[i] if exemplars else None)
    return h.state()


# -- mergeable-histogram properties ------------------------------------------

def test_merge_commutative_and_associative():
    # Dyadic values: float sums are exact, so the property holds as
    # full dict equality, not approximately.
    a = _hist_state([0.03125, 0.25, 0.75])
    b = _hist_state([0.0078125, 0.0078125, 2.0])
    c = _hist_state([0.0625] * 5)
    assert merge_hist_states(a, b) == merge_hist_states(b, a)
    assert (merge_hist_states(merge_hist_states(a, b), c)
            == merge_hist_states(a, merge_hist_states(b, c)))
    # Merging is non-destructive: the inputs are unchanged.
    assert a == _hist_state([0.03125, 0.25, 0.75])


def test_merge_equals_single_registry_ground_truth():
    """Per-replica states merged == ONE histogram that saw everything:
    the bucket-exact contract fleet percentiles rest on."""
    import numpy as np

    rng = np.random.default_rng(3)
    shards = [rng.exponential(0.1, size=n).tolist() for n in (40, 17, 93)]
    merged = merge_hist_states(*(_hist_state(s) for s in shards))
    truth = _hist_state([v for s in shards for v in s])
    assert merged["counts"] == truth["counts"]
    assert merged["count"] == truth["count"]
    assert merged["sum"] == pytest.approx(truth["sum"])
    assert merged["min"] == truth["min"]
    assert merged["max"] == truth["max"]
    for q in (50, 90, 99):
        assert (hist_state_percentile(merged, q)
                == pytest.approx(hist_state_percentile(truth, q)))


def test_merge_keeps_worst_exemplar_per_bucket():
    a = _hist_state([0.02, 0.3], exemplars=["a1", "a2"])
    b = _hist_state([0.03, 0.4], exemplars=["b1", "b2"])
    m = merge_hist_states(a, b)
    got = {tuple(e) for e in m["exemplars"] if e is not None}
    assert (0.03, "b1") in got  # 0.03 > 0.02 in the same bucket
    assert (0.4, "b2") in got   # 0.4 > 0.3
    with pytest.raises(ValueError, match="layout"):
        merge_hist_states(a, _hist_state([0.1], buckets=(1.0, 2.0)))


# -- DeltaEncoder -------------------------------------------------------------

def test_delta_encoder_ships_only_changes():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", buckets=BUCKETS)
    c = reg.counter("reqs_total")
    g = reg.gauge("depth")
    h.observe(0.02, exemplar="t1")
    c.inc(3)
    g.set(5)
    enc = DeltaEncoder(reg)
    d1 = enc.delta()
    assert d1["seq"] == 1
    assert d1["hists"]["lat_seconds"]["count"] == 1
    assert d1["counters"]["reqs_total"] == 3
    assert d1["gauges"]["depth"] == 5
    # Quiet registry: nothing shipped but the gauges (no delta exists
    # for a gauge).
    d2 = enc.delta()
    assert d2["hists"] == {} and d2["counters"] == {}
    # New traffic ships ONLY the increment.
    h.observe(0.7)
    c.inc()
    d3 = enc.delta()
    assert d3["hists"]["lat_seconds"]["count"] == 1  # not 2
    assert d3["counters"]["reqs_total"] == 1
    # full=True re-ships everything (the re-sync path).
    d4 = enc.delta(full=True)
    assert d4["hists"]["lat_seconds"]["count"] == 2
    assert d4["counters"]["reqs_total"] == 4


def test_delta_encoder_reset_source_reships_full_value():
    """A restarted replica's counter went backwards from the encoder's
    view: the full new value ships as the delta (never a negative)."""
    reg = MetricsRegistry()
    reg.counter("reqs_total").inc(2)
    enc = DeltaEncoder(reg)
    enc.delta()
    enc._counter_prev["reqs_total"] = 99.0  # simulate the old incarnation
    reg.counter("reqs_total").inc()
    d = enc.delta()
    assert d["counters"]["reqs_total"] == 3.0


def test_metric_key_roundtrip():
    reg = MetricsRegistry()
    reg.counter("c_total", tenant="t1", zone="z")
    (m,) = reg.collect()
    key = DeltaEncoder.metric_key(m)
    assert key == "c_total{tenant=t1,zone=z}"
    assert DeltaEncoder.parse_key(key) == ("c_total",
                                           {"tenant": "t1", "zone": "z"})
    assert DeltaEncoder.parse_key("bare") == ("bare", {})


# -- TimeSeriesStore ----------------------------------------------------------

def test_store_rolls_windows_and_skips_gaps():
    clk = FakeClock()
    store = TimeSeriesStore(window_s=1.0, capacity=8, clock=clk)
    store.record_hist("m", _hist_state([0.02]))
    clk.advance(1.0)
    store.record_hist("m", _hist_state([0.3]))
    clk.advance(10.0)  # a quiet gap: no empty windows minted
    store.record_hist("m", _hist_state([0.7]))
    store.flush()
    windows = store.query("m")
    assert len(windows) == 3
    assert all("hist" in w for w in windows)
    # Span restriction: only the trailing window survives a 2s cut.
    recent = store.query("m", span_s=2.0)
    assert len(recent) == 1
    assert recent[0]["hist"]["count"] == 1


def test_store_capacity_bounds_memory():
    clk = FakeClock()
    store = TimeSeriesStore(window_s=1.0, capacity=4, clock=clk)
    for _ in range(10):
        store.record_value("c", 1.0)
        clk.advance(1.0)
    assert len(store.query("c")) == 4  # oldest evicted, newest kept
    s = store.summary("c")
    assert s["value"] == 4.0


def test_store_summary_is_bucket_exact_merge():
    clk = FakeClock()
    store = TimeSeriesStore(window_s=1.0, capacity=8, clock=clk)
    shard_a, shard_b = [0.02, 0.3, 0.09], [0.7, 0.005]
    store.record_hist("m", _hist_state(shard_a))
    clk.advance(1.0)
    store.record_hist("m", _hist_state(shard_b))
    store.flush()
    s = store.summary("m")
    truth = _hist_state(shard_a + shard_b)
    assert s["count"] == truth["count"]
    assert s["hist"]["counts"] == truth["counts"]
    assert s["p99"] == pytest.approx(hist_state_percentile(truth, 99))
    assert store.summary("absent") is None


def test_store_gauge_keeps_window_max_and_last():
    clk = FakeClock()
    store = TimeSeriesStore(window_s=1.0, clock=clk)
    store.record_gauge("g", 0.5)
    store.record_gauge("g", 0.9)
    store.record_gauge("g", 0.2)
    store.flush()
    (w,) = store.query("g")
    assert w["gauge"] == 0.9 and w["last"] == 0.2
    s = store.summary("g")
    assert s["gauge_max"] == 0.9 and s["gauge_last"] == 0.2


def test_store_rejects_bad_window():
    with pytest.raises(ValueError):
        TimeSeriesStore(window_s=0)


# -- FleetAggregator ----------------------------------------------------------

def _payload(reg, enc=None, **delta_kwargs):
    return (enc or DeltaEncoder(reg)).delta(**delta_kwargs)


def test_fleet_fold_per_replica_and_fleet_series():
    import numpy as np

    fleet = FleetAggregator(TimeSeriesStore(window_s=1.0,
                                            clock=FakeClock()))
    rng = np.random.default_rng(11)
    raw: list[float] = []
    regs = {rid: MetricsRegistry() for rid in ("r0", "r1", "r2")}
    encs = {rid: DeltaEncoder(reg) for rid, reg in regs.items()}
    # Several push rounds with interleaved traffic, like the real plane.
    for _ in range(3):
        for rid, reg in regs.items():
            xs = rng.exponential(0.1, size=5).tolist()
            raw.extend(xs)
            h = reg.histogram("serving_ttft_seconds", buckets=BUCKETS)
            for v in xs:
                h.observe(v)
            reg.counter("serving_requests_completed_total").inc(5)
            reg.gauge("serving_slot_occupancy").set(0.5)
            fleet.ingest(rid, "decode", encs[rid].delta())
    truth = _hist_state(raw)
    merged = fleet.fleet_hist_state("serving_ttft_seconds")
    assert merged["counts"] == truth["counts"]
    for q in (50, 99):
        # The windowed fleet percentile == offline recompute over the
        # pooled raw samples' histogram (bucket-exact end to end).
        assert (hist_state_percentile(merged, q)
                == pytest.approx(hist_state_percentile(truth, q)))
    snap = fleet.registry.snapshot()
    assert snap["serving_ttft_seconds{fleet=all}"]["count"] == len(raw)
    assert snap["serving_ttft_seconds{replica=r1,role=decode}"][
        "count"] == len(raw) // 3
    assert snap[
        "serving_requests_completed_total{fleet=all}"]["value"] == 45
    st = fleet.stats()
    assert st["pushes"] == 9 and st["push_errors"] == 0
    assert st["replicas"] == {"r0": 3, "r1": 3, "r2": 3}
    assert fleet.staleness_s() is not None
    # The store got the fleet-wide series too.
    fleet.store.flush()
    assert fleet.store.summary("serving_ttft_seconds")["count"] == len(raw)


def test_fleet_forget_replica_drops_only_gauges():
    fleet = FleetAggregator()
    reg = MetricsRegistry()
    reg.histogram("serving_ttft_seconds", buckets=BUCKETS).observe(0.02)
    reg.gauge("serving_slot_occupancy").set(1.0)
    fleet.ingest("r0", "decode", DeltaEncoder(reg).delta())
    fleet.forget_replica("r0")
    snap = fleet.registry.snapshot()
    assert not any("slot_occupancy" in k and "r0" in k for k in snap)
    # Counted history stays: those requests happened.
    assert snap["serving_ttft_seconds{fleet=all}"]["count"] == 1
    assert fleet.stats()["replicas"] == {}


def test_fleet_malformed_payload_counts_error_not_raise():
    fleet = FleetAggregator()
    fleet.ingest("r0", "decode", {"hists": {"m": {"not": "a state"}}})
    assert fleet.stats()["push_errors"] == 1


# -- SLOEngine ----------------------------------------------------------------

def test_objective_validation_is_loud():
    with pytest.raises(ValueError, match="kind"):
        Objective(name="x", kind="vibes", target=0.9)
    with pytest.raises(ValueError, match="target"):
        Objective(name="x", kind="latency", target=1.5, metric="m")
    with pytest.raises(ValueError, match="metric"):
        Objective(name="x", kind="latency", target=0.9)
    with pytest.raises(ValueError, match="bad and total"):
        Objective(name="x", kind="ratio", target=0.9)
    store = TimeSeriesStore(clock=FakeClock())
    dup = [Objective(name="x", kind="gauge", target=0.9, metric="m")] * 2
    with pytest.raises(ValueError, match="duplicate"):
        SLOEngine(store, objectives=dup)
    with pytest.raises(ValueError, match="window"):
        SLOEngine(store, fast_window_s=10.0, slow_window_s=5.0)


def _latency_engine(clk, **kw):
    store = TimeSeriesStore(window_s=1.0, capacity=64, clock=clk)
    obj = Objective(name="lat_p", kind="latency", target=0.9,
                    metric="m", threshold=0.07)
    eng = SLOEngine(store, objectives=[obj], fast_window_s=2.0,
                    slow_window_s=10.0, warn_burn=2.0, page_burn=5.0,
                    clock=clk, **kw)
    return store, eng


def test_latency_threshold_snaps_to_bucket_bound():
    clk = FakeClock()
    store, eng = _latency_engine(clk)
    store.record_hist("m", _hist_state([0.02]))
    store.flush()
    (r,) = eng.evaluate()
    # 0.07 is inside (0.05, 0.1]: the effective bound is 0.1 and the
    # bad fraction is the EXACT tail mass above it.
    assert r["fast"]["threshold_effective"] == 0.1
    assert r["state"] == "ok" and eng.overall() == "ok"


def test_multiwindow_rule_needs_both_windows_burning():
    """Fast window saturated with bad samples, slow window diluted by
    older good traffic: no page — the classic blip guard."""
    clk = FakeClock()
    store, eng = _latency_engine(clk)
    store.record_hist("m", _hist_state([0.02] * 97))  # good, t=0
    clk.advance(5.0)                                  # outside fast span
    store.record_hist("m", _hist_state([0.9] * 3))    # bad burst, now
    store.flush()
    (r,) = eng.evaluate()
    assert r["fast"]["burn"] >= 5.0       # fast alone would page
    assert r["slow"]["burn"] < 2.0        # slow says blip
    assert r["state"] == "ok"


def test_state_machine_walks_ok_warn_page_ok_with_exemplars():
    clk = FakeClock()
    store, eng = _latency_engine(clk)
    # Healthy traffic -> ok.
    store.record_hist("m", _hist_state([0.02] * 10))
    store.flush()
    assert eng.evaluate()[0]["state"] == "ok"
    # ~30% above the bound in BOTH windows: burn 3 in [2, 5) -> warn.
    clk.advance(1.0)
    store.record_hist("m", _hist_state(
        [0.02] * 4 + [0.9] * 6, exemplars=[None] * 4 + [f"t{i}"
                                           for i in range(6)]))
    store.flush()
    assert eng.evaluate()[0]["state"] == "warn"
    # Saturate with bad samples -> page, carrying exemplar trace ids.
    # 0.95 > the warn phase's 0.9: the merge keeps the strictly-worst
    # exemplar per bucket, so the page event must carry "slow1".
    clk.advance(1.0)
    store.record_hist("m", _hist_state([0.95] * 40,
                                       exemplars=["slow1"] * 40))
    store.flush()
    r = eng.evaluate()[0]
    assert r["state"] == "page" and eng.overall() == "page"
    # Quiet windows drain the burn -> back to ok (idle burns nothing).
    clk.advance(11.0)
    store.record_hist("m", _hist_state([0.02]))
    store.flush()
    assert eng.evaluate()[0]["state"] == "ok"
    transitions = [(e["from"], e["to"]) for e in eng.events]
    assert transitions == [("ok", "warn"), ("warn", "page"),
                           ("page", "ok")]
    breach = [e for e in eng.events if e["to"] in ("warn", "page")]
    assert all(e["exemplars"] for e in breach)
    assert "slow1" in [x for e in breach for x in e["exemplars"]]
    snap = eng.snapshot()
    assert snap["overall"] == "ok"
    assert snap["evaluations"] == 4 and snap["eval_cost_s"] >= 0
    assert len(snap["events"]) == 3


def test_ratio_objective_pages_on_error_budget():
    clk = FakeClock()
    store = TimeSeriesStore(window_s=1.0, clock=clk)
    obj = Objective(name="errors", kind="ratio", target=0.99,
                    bad=("rej_total",), total=("rej_total", "ok_total"))
    eng = SLOEngine(store, objectives=[obj], fast_window_s=2.0,
                    slow_window_s=10.0, clock=clk)
    store.record_value("ok_total", 99.0)
    store.record_value("rej_total", 1.0)
    store.flush()
    (r,) = eng.evaluate()
    assert r["fast"]["burn"] == pytest.approx(1.0)  # exactly at budget
    assert r["state"] == "ok"
    clk.advance(1.0)
    store.record_value("rej_total", 50.0)
    store.record_value("ok_total", 50.0)
    store.flush()
    (r,) = eng.evaluate()
    assert r["state"] == "page"  # burn far past 14.4 in both windows


def test_gauge_objective_counts_time_above_threshold():
    clk = FakeClock()
    store = TimeSeriesStore(window_s=1.0, clock=clk)
    obj = Objective(name="pressure", kind="gauge", target=0.5,
                    metric="occ", threshold=0.95)
    eng = SLOEngine(store, objectives=[obj], fast_window_s=4.0,
                    slow_window_s=10.0, clock=clk)
    for v in (0.5, 0.99, 0.99, 0.2):
        store.record_gauge("occ", v)
        clk.advance(1.0)
    store.flush()
    (r,) = eng.evaluate()
    # 2 of 4 windows above threshold = bad fraction 0.5, budget 0.5:
    # burn 1.0 -> sustainable, ok.
    assert r["fast"]["bad_fraction"] == pytest.approx(0.5)
    assert r["state"] == "ok"


def test_no_data_burns_nothing():
    clk = FakeClock()
    store, eng = _latency_engine(clk)
    (r,) = eng.evaluate()
    assert r["state"] == "ok"
    assert r["fast_burn"] == 0.0 and "fast" not in r
