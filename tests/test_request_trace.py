"""Per-request distributed tracing, flight recorder, debugz.

Covers the observability layer end to end:

- unit: TimelineRecord/TraceStore bounds + Chrome export (one lane per
  request, every event carries its trace_id), FlightRecorder ring
  semantics (fixed slots, overwrite-oldest) and dump round trips;
- engine: a traced request's timeline carries queue wait, prefill
  chunks with device time, first token, terminal status; the debugz
  verb's slot/queue tables; histogram exemplars name the worst request;
- the disabled path: no store/recorder -> no timeline objects at all,
  and TTFT exemplars still work (they ride the always-on trace_id);
- the armed RecompileAuditor stays silent with tracing + flight
  recorder + SLO all on (tracing must not perturb the compiled step);
- cluster: trace-id CONTINUITY across a router retry — chaos-kill a
  replica mid-queue and the merged tracez shows both replica hops under
  ONE trace_id; a mid-stream loss's replica_lost error carries the
  trace_id; a chaos-killed replica leaves a flight-recorder dump the
  supervisor references in its restart log.
"""

import asyncio
import json
import time

import numpy as np
import pytest

from distkeras_tpu.models.bert import gpt_tiny
from distkeras_tpu.serving import (
    LocalReplica,
    ServingClient,
    ServingCluster,
    ServingEngine,
)
from distkeras_tpu.serving.client import ServerError
from distkeras_tpu.serving.server import ServingServer
from distkeras_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    RecompileAuditor,
    TimelineRecord,
    TraceStore,
    chrome_trace,
    load_flight_dump,
    merge_trace,
    new_trace_id,
)

VOCAB = 64

SUP = dict(health_interval_s=0.05, health_timeout_s=2.0, fail_after=2,
           base_delay_s=0.05, max_delay_s=1.0, stable_after_s=0.5)


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(0)


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


# -- units --------------------------------------------------------------------

def test_trace_store_bounds_and_chrome_export():
    store = TraceStore(capacity=3)
    tids = [new_trace_id() for _ in range(5)]
    assert len(set(tids)) == 5
    for tid in tids:
        rec = TimelineRecord(tid, "engine", "r0")
        rec.event("submit", prompt_tokens=4)
        rec.event("admit", dur_s=0.01)
        rec.data["status"] = "ok"
        store.put(rec)
    assert len(store) == 3 and store.evicted == 2
    assert store.get(tids[0]) is None  # oldest evicted
    assert store.get(tids[-1])["data"]["status"] == "ok"

    ct = chrome_trace(store.recent(10))
    names = [e for e in ct["traceEvents"] if e["ph"] == "M"]
    assert len(names) == 3  # one lane (metadata name) per request
    lanes = {e["tid"] for e in ct["traceEvents"]}
    assert len(lanes) == 3
    # Every non-metadata event carries its trace_id; dur_s events render
    # as complete slices.
    body = [e for e in ct["traceEvents"] if e["ph"] != "M"]
    assert all(e["args"]["trace_id"] in tids for e in body)
    assert any(e["ph"] == "X" and e["dur"] > 0 for e in body)


def test_trace_store_keeps_multiple_hops_per_id():
    store = TraceStore(capacity=8)
    tid = new_trace_id()
    for src in ("r0", "r1"):
        rec = TimelineRecord(tid, "engine", src)
        rec.event("submit")
        store.put(rec)
    hops = store.get_all(tid)
    assert [h["source"] for h in hops] == ["r0", "r1"]
    merged = merge_trace(tid, hops)
    assert merged["hops"] == ["r0", "r1"]
    assert [e[2] for e in merged["events"]] == ["submit", "submit"]


def test_flight_recorder_ring_and_dump(tmp_path):
    fr = FlightRecorder(capacity=4, timeline_capacity=2, slow_capacity=2,
                        dump_path=str(tmp_path / "black_box.json"),
                        source="r7")
    for i in range(7):
        fr.record_event(f"e{i}", n=i)
    # Fixed slots, overwrite-oldest: only the last `capacity` survive.
    assert [e[1] for e in fr._events.items()] == ["e3", "e4", "e5", "e6"]
    assert fr.stats()["events_recorded"] == 7

    for i in range(3):
        fr.record_timeline({"trace_id": f"t{i}", "data": {}}, slow=(i == 1))
    path = fr.dump()
    dump = load_flight_dump(path)
    assert dump["source"] == "r7"
    assert [e["kind"] for e in dump["events"]] == ["e3", "e4", "e5", "e6"]
    assert [t["trace_id"] for t in dump["timelines"]] == ["t1", "t2"]
    assert [t["trace_id"] for t in dump["slow_exemplars"]] == ["t1"]

    # crash_dump never raises, even with an unwritable path.
    bad = FlightRecorder(capacity=2, dump_path="/nonexistent-dir/x.json")
    assert bad.crash_dump(error="boom") is None


def test_histogram_exemplars_track_worst_per_bucket():
    reg = MetricsRegistry()
    h = reg.histogram("t_seconds", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="fast")
    h.observe(0.08, exemplar="fast2")
    h.observe(0.5, exemplar="mid")
    h.observe(5.0, exemplar="slow")
    ex = h.exemplars()
    assert ex["0.1"]["trace_id"] == "fast2" and ex["0.1"]["value"] == 0.08
    assert ex["1.0"]["trace_id"] == "mid"
    assert ex["+Inf"]["trace_id"] == "slow"
    snap = reg.snapshot()["t_seconds"]
    assert snap["exemplars"]["+Inf"]["trace_id"] == "slow"


# -- engine + server ----------------------------------------------------------

def test_engine_timeline_debugz_and_auditor_silence(lm, rng, artifact_dir):
    """One traced request through the full server: the timeline record
    has the canonical phases, debugz shows the live tables, exemplars
    name the request — and the ARMED auditor proves tracing + flight
    recorder + SLO never retrace the decode step."""
    model, variables = lm
    store = TraceStore(64)
    recorder = FlightRecorder(
        64, dump_path=str(artifact_dir / "flight-single.json"), source="e0")
    engine = ServingEngine(
        model, variables, slots=2, max_queue=8,
        prefix_cache_mb=1.0, prefix_block_tokens=4, prefill_chunk=4,
        auditor=RecompileAuditor(), arm_auditor_after_warmup=True,
        trace_store=store, flight_recorder=recorder,
        slo_s=1e-9)  # everything violates: exercises the slow ring
    prompt = _prompt(rng, 9)

    async def go():
        server = ServingServer(engine, port=0)
        await server.start()
        try:
            async with ServingClient("127.0.0.1", server.port) as c:
                my_tid = new_trace_id()
                done = await c.generate(prompt, 5, trace_id=my_tid)
                assert done["trace_id"] == my_tid
                assert c.last_trace_id == my_tid
                # Second request warms the prefix cache path too.
                done2 = await c.generate(prompt[:8] + _prompt(rng, 3), 4)
                dz = await c.debugz()
                tz = await c.tracez(my_tid)
                health = await c.healthz()
                metrics = await c.metricsz()
            return done, done2, dz, tz, health, metrics
        finally:
            await server.stop(drain=True)

    done, done2, dz, tz, health, metrics = asyncio.run(go())
    tid = done["trace_id"]

    # Timeline: canonical phases in order, with the summary data.
    hops = tz["hops"]
    assert len(hops) == 1 and hops[0]["trace_id"] == tid
    names = [e[0] for e in hops[0]["events"]]
    assert names[0] == "submit" and names[-1] == "done"
    assert "admit" in names and "first_token" in names
    assert names.count("prefill_chunk") == hops[0]["data"]["prefill_chunks"]
    d = hops[0]["data"]
    assert d["status"] == "ok" and d["tokens_out"] == 5
    assert d["queue_wait_s"] >= 0 and d["prefill_device_s"] > 0
    assert d["prompt_tokens"] == len(prompt)
    assert d["slo_violation"] is True  # the 1ns SLO
    assert d["decode_iterations"] >= 1

    # debugz: slot/queue tables, prefix-cache families, recorder stats.
    assert [s["state"] for s in dz["slots"]] == ["free", "free"]
    assert dz["queue"]["depth"] == 0
    assert dz["prefix_cache"]["families"] >= 1
    fam = dz["prefix_cache"]["top_families"][0]
    assert fam["blocks"] >= 1 and fam["tokens"] >= 4
    assert dz["flight_recorder"]["timelines_recorded"] == 2
    assert dz["slo_s"] == 1e-9

    # healthz/metricsz: SLO counter + exemplars riding the trace_id.
    assert health["slo_violations"] == 2
    ttft_ex = metrics["serving_ttft_seconds"]["exemplars"]
    # Only two requests ran: every bucket's worst sample names one.
    assert ttft_ex and all(v["trace_id"] in (tid, done2["trace_id"])
                           for v in ttft_ex.values())
    itl_ex = metrics["serving_inter_token_seconds"]["exemplars"]
    assert itl_ex, "inter-token histogram recorded no exemplars"
    assert metrics["serving_slo_violations_total"]["value"] == 2

    # Flight recorder: both timelines in the ring, both slow exemplars.
    assert len(recorder.slow_exemplars()) == 2

    # THE invariant: all of it on, decode still compiled exactly once.
    assert engine.auditor.compiles("serving_decode") == 1
    assert engine.auditor.report()["serving_decode"]["armed"]
    # Artifacts for CI's on-failure upload: black box, metrics snapshot
    # JSONL, and the one-lane-per-request Chrome trace.
    from distkeras_tpu.telemetry import write_snapshot_jsonl

    recorder.dump()
    write_snapshot_jsonl(engine.metrics.registry,
                         str(artifact_dir / "metrics-snapshot.jsonl"))
    store.export_chrome_trace(str(artifact_dir / "request-trace.json"))
    exported = json.load(open(artifact_dir / "request-trace.json"))
    lanes = {e["tid"] for e in exported["traceEvents"]}
    assert len(lanes) == 2  # one lane per request


def test_disabled_path_builds_no_timelines(lm, rng):
    """No store, no recorder, no SLO: requests never grow a timeline
    object (the per-token path has nothing to touch), yet trace ids
    still flow end to end for correlation."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1, max_queue=4)
    assert engine.trace_store is None and engine.flight_recorder is None

    async def go():
        task = asyncio.create_task(engine.run())
        req = engine.submit(_prompt(rng, 5), 4, trace_id="cafe01")
        assert req.trace is None  # never built
        out = await req.result()
        engine.shutdown(drain=True)
        await task
        return req, out

    req, out = asyncio.run(go())
    assert req.trace is None and req.trace_id == "cafe01"
    assert len(out) == 4
    # Exemplars still recorded (they ride the always-present trace_id).
    snap = engine.metrics.registry.snapshot()
    assert any(v["trace_id"] == "cafe01"
               for v in snap["serving_ttft_seconds"]["exemplars"].values())


def test_engine_crash_dumps_flight_recorder(lm, rng, tmp_path):
    """The run loop dying (cancellation == LocalReplica chaos kill)
    writes the last-words dump before the exception propagates."""
    model, variables = lm
    path = str(tmp_path / "last_words.json")
    engine = ServingEngine(
        model, variables, slots=1, max_queue=4,
        flight_recorder=FlightRecorder(32, dump_path=path, source="dying"))

    async def go():
        task = asyncio.create_task(engine.run())
        req = engine.submit(_prompt(rng, 20), 10)
        async for _ in req.tokens():
            break  # mid-stream
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task

    asyncio.run(go())
    dump = load_flight_dump(path)
    assert dump["source"] == "dying"
    kinds = [e["kind"] for e in dump["events"]]
    assert "engine_start" in kinds and "crash" in kinds


# -- cluster: trace continuity across retry -----------------------------------

def _traced_factory(lm_pair, recorders, dump_dir, **engine_kwargs):
    """LocalReplica factory whose engines all carry trace stores and
    flight recorders dumping under ``dump_dir``."""
    model, variables = lm_pair

    def make(i):
        def build():
            recorder = FlightRecorder(
                128, dump_path=str(dump_dir / f"flight-r{i}.json"),
                source=f"r{i}")
            recorders[i] = recorder
            eng = ServingEngine(
                model, variables, slots=2, max_queue=16,
                trace_store=TraceStore(256), flight_recorder=recorder,
                **engine_kwargs)
            eng.trace_source = f"r{i}"
            return eng

        return LocalReplica(build)

    return make


def test_trace_continuity_across_router_retry(lm, rng, artifact_dir):
    """Chaos-kill a replica while requests are queued on it: a retried
    (zero-streamed) request's MERGED timeline shows both replica hops
    under one trace_id; any mid-stream loss's replica_lost error carries
    its trace_id; and the killed replica's flight-recorder dump lands in
    the supervisor's restart log."""
    prompts = [_prompt(rng, 4 + (i % 5)) for i in range(12)]

    async def go():
        recorders = {}
        cluster = ServingCluster(
            _traced_factory(lm, recorders, artifact_dir), 2,
            supervisor_kwargs=SUP, registry=MetricsRegistry())
        results, failures = {}, {}

        async def client_task(idx, p):
            streamed = []
            c = ServingClient("127.0.0.1", cluster.port)
            try:
                done = await c.generate(p, 8, on_token=streamed.append)
                results[idx] = done
            except (ServerError, ConnectionError) as e:
                failures[idx] = (e, len(streamed), c.last_trace_id)
            finally:
                await c.aclose()

        async with cluster:
            tasks = [asyncio.create_task(client_task(i, p))
                     for i, p in enumerate(prompts)]
            while len(results) < 2:
                await asyncio.sleep(0.01)
            await cluster.replicas["r0"].handle.kill()
            await asyncio.gather(*tasks)

            # Merged traces come off the router while it can still reach
            # the surviving + restarted replicas.
            deadline = time.monotonic() + 30
            while cluster.supervisor.ready_count < 2:
                assert time.monotonic() < deadline, "no restart"
                await asyncio.sleep(0.02)
            async with ServingClient("127.0.0.1", cluster.port) as c:
                merged = {idx: await c.tracez(done["trace_id"])
                          for idx, done in results.items()}
            log = cluster.supervisor.restart_log_entries()
        return results, failures, merged, log

    results, failures, merged, log = asyncio.run(go())

    # Completions all carry ids; find one that retried (two hops).
    retried = {idx: m for idx, m in merged.items()
               if m["router"] and m["router"]["data"].get("retries", 0) > 0}
    assert retried, "chaos kill produced no zero-streamed retry"
    for idx, m in retried.items():
        tid = results[idx]["trace_id"]
        assert m["trace_id"] == tid
        router_hops = m["router"]["data"]["hops"]
        assert len(router_hops) >= 2, (
            f"retried request {tid} shows hops {router_hops}")
        assert "retry" in [e[2] for e in m["events"]]
        # The SECOND hop's engine timeline survived (the first died with
        # r0 — its record is in r0's flight dump, referenced below).
        assert any(h["data"].get("status") == "ok"
                   for h in m["engine_hops"])
        assert all(h["trace_id"] == tid for h in m["engine_hops"])

    # Mid-stream losses carry the trace_id on the typed error. A killed
    # LocalReplica's handlers may flush the replica's own engine-failure
    # line ("error") before the connection drops ("replica_lost") — both
    # are mid-stream terminal errors and both must name the request.
    # (test_replica_lost_error_carries_trace_id forces the pure
    # connection-drop path deterministically.)
    for idx, (err, streamed, tid) in failures.items():
        assert streamed >= 1
        if isinstance(err, ServerError):
            assert err.code in ("replica_lost", "error"), err.code
            assert err.trace_id == tid, (
                f"mid-stream {err.code} error lost its trace_id: {err}")

    # The supervisor's restart log references r0's last-words dump, and
    # the dump itself holds timelines from before the kill.
    death = [e for e in log if e.get("rid") == "r0" and "why" in e]
    assert death, log
    assert death[0]["flight_recorder"].endswith("flight-r0.json")
    assert isinstance(death[0]["last_words"], dict)
    dump = load_flight_dump(death[0]["flight_recorder"])
    assert dump["source"] == "r0"
    assert any(e["kind"] == "crash" for e in dump["events"])
    restarted = [e for e in log if e.get("restarted")]
    assert restarted and restarted[0]["rid"] == "r0"


def test_replica_lost_error_carries_trace_id():
    """Force the router's OWN mid-stream loss path: a backend that
    streams one token and then drops the connection (no terminal line,
    the SIGKILL wire shape). The client's typed replica_lost error must
    carry the request's trace_id."""
    from distkeras_tpu.serving.cluster.replicas import READY, ReplicaHandle
    from distkeras_tpu.serving.cluster.router import Router
    from distkeras_tpu.serving.cluster.supervisor import ReplicaSupervisor

    class _FakeHandle(ReplicaHandle):
        alive = True

        async def start(self):
            raise NotImplementedError

        async def kill(self):
            pass

        async def terminate(self):
            pass

    async def backend(reader, writer):
        await reader.readline()
        writer.write(b'{"token": 7}\n')
        await writer.drain()
        writer.close()  # dies mid-stream

    async def go():
        srv = await asyncio.start_server(backend, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        sup = ReplicaSupervisor(lambda i: _FakeHandle(), 1,
                                base_delay_s=60.0)  # park any restart
        info = sup.replicas["r0"]
        info.host, info.port, info.status = "127.0.0.1", port, READY
        router = Router(sup, port=0, trace_capacity=16)
        await router.start()
        try:
            c = ServingClient("127.0.0.1", router.port)
            with pytest.raises(ServerError) as ei:
                await c.generate([1, 2, 3], 4, trace_id="feed1234")
            await c.aclose()
            merged = (await router._tracez({"cmd": "tracez",
                                           "trace_id": "feed1234"}))
        finally:
            await router.stop()
            await sup.stop()
            srv.close()
        return ei.value, merged["tracez"]

    err, trace = asyncio.run(go())
    assert err.code == "replica_lost"
    assert err.trace_id == "feed1234"
    assert trace["router"]["data"]["status"] == "replica_lost"
    assert [e[2] for e in trace["events"]][0] == "request"


def test_router_debugz_aggregates_fleet(lm, rng, tmp_path):
    async def go():
        recorders = {}
        cluster = ServingCluster(
            _traced_factory(lm, recorders, tmp_path, slo_s=30.0), 2,
            supervisor_kwargs=SUP, registry=MetricsRegistry())
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port) as c:
                await c.generate(_prompt(rng, 5), 4)
                dz = await c.debugz()
        return dz

    dz = asyncio.run(go())
    assert dz["router"]["replicas_ready"] == 2
    assert dz["router"]["trace_store"]["records"] == 1
    assert set(dz["replicas"]) == {"r0", "r1"}
    for rid, entry in dz["replicas"].items():
        sub = entry["debugz"]
        assert len(sub["slots"]) == 2
        assert sub["queue"]["depth"] == 0
        assert sub["flight_recorder"]["source"] == rid
        assert sub["slo_s"] == 30.0
    # The pretty printer renders both shapes without blowing up.
    from distkeras_tpu.serving.debugz import format_debugz

    page = format_debugz(dz)
    assert "router: 2/2 ready" in page and "replica r0" in page


def test_debugz_cli_json(lm, rng):
    """`run.py debugz` against a live server: the subcommand fetches and
    prints both the JSON payload and the pretty page. The server runs on
    a daemon thread's event loop because debugz_main owns its own
    asyncio.run."""
    import contextlib
    import io
    import threading

    from distkeras_tpu.run import debugz_main

    model, variables = lm
    engine = ServingEngine(model, variables, slots=1, max_queue=4,
                           trace_store=TraceStore(16))
    started = threading.Event()
    holder: dict = {}

    def serve_forever():
        async def go():
            server = ServingServer(engine, port=0)
            await server.start()
            holder["port"] = server.port
            holder["stop"] = asyncio.Event()
            started.set()
            await holder["stop"].wait()
            await server.stop(drain=True)

        holder["loop"] = asyncio.new_event_loop()
        holder["loop"].run_until_complete(go())

    t = threading.Thread(target=serve_forever, daemon=True)
    t.start()
    assert started.wait(30)
    try:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = debugz_main(["--host", "127.0.0.1",
                              "--port", str(holder["port"]), "--json"])
        assert rc == 0
        payload = json.loads(buf.getvalue())
        assert [s["state"] for s in payload["slots"]] == ["free"]
        buf2 = io.StringIO()
        with contextlib.redirect_stdout(buf2):
            assert debugz_main(["--host", "127.0.0.1",
                                "--port", str(holder["port"])]) == 0
        assert "active_slots=0" in buf2.getvalue()
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=30)


def test_tracing_shim_deprecation_warning():
    from distkeras_tpu import tracing

    from distkeras_tpu.telemetry import spans

    with pytest.warns(DeprecationWarning, match="distkeras_tpu.telemetry"):
        assert tracing.span is spans.span
    with pytest.warns(DeprecationWarning):
        assert tracing.Tracer is spans.Tracer
