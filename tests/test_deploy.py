"""Continuous deployment (distkeras_tpu.deploy): publish -> canary -> roll.

The loop under test closes ROADMAP open item 4: a trainer publishes
stamped weight files + an atomic manifest; a DeployController watching
the directory validates each candidate, canaries it on one drained
replica (golden prompts + finite golden-batch loss), rolls it through
the router's zero-downtime reload, and rolls back + quarantines on any
failure. Invariants asserted here:

- served provenance flips with each deploy: done lines carry the NEW
  ``(version, digest)`` after a roll and the OLD one before it, with no
  client-visible error at any point (>= N-1 replicas serving);
- a corrupted publish (NaN weights, wrong shapes, a canary latency
  breach) never reaches the fleet: it is rejected at the right stage,
  quarantined with a reason record served by ``deployz``, and the
  canary replica is restored to last-good;
- the armed RecompileAuditor is silent across every reload — weight
  churn costs ZERO decode retraces;
- the rolling reload's reply names each replica's before/after
  ``(version, digest)`` so a roll is verifiable from one reply;
- trainers actually publish: the step-loop family per step, the async
  family from the PS-center thread, both leaving a readable manifest.
"""

import asyncio
import json
import os
import time

import numpy as np
import pytest

from distkeras_tpu.checkpoint import (
    load_weights_file_with_provenance,
    publish_weights,
    read_manifest,
    weights_provenance,
)
from distkeras_tpu.deploy import (
    PublishPolicy,
    WeightPublisher,
    parse_publish_every,
)
from distkeras_tpu.deploy.harness import wire_controller
from distkeras_tpu.models.bert import gpt_tiny
from distkeras_tpu.serving import (
    LocalReplica,
    ServingClient,
    ServingCluster,
    ServingEngine,
)
from distkeras_tpu.telemetry import MetricsRegistry, RecompileAuditor

VOCAB = 64

# Fast probing but contention-tolerant death detection: the full tier-1
# suite can stall this one event loop for seconds at a time (jax
# compiles in neighboring tests), and a spurious probe timeout must not
# kill a healthy replica mid-deploy.
SUP = dict(health_interval_s=0.05, health_timeout_s=5.0, fail_after=4,
           base_delay_s=0.05, max_delay_s=1.0, stable_after_s=0.5)


async def _publish(d, variables, **meta):
    """Publish OFF the event loop: model.init + serialization can stall
    a shared loop long enough to time out health probes."""
    return await asyncio.to_thread(
        publish_weights, d, variables, meta=meta or None)


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(0)


def _cluster(lm_pair, boot_path, engines=None, n=2):
    """2-replica LocalReplica cluster booted from a published weights
    FILE (each engine carries the file's provenance stamp), every engine
    under its own armed RecompileAuditor."""
    model, variables = lm_pair

    def factory(i):
        def build():
            v, prov = load_weights_file_with_provenance(
                boot_path, like=variables)
            eng = ServingEngine(model, v, slots=2, max_queue=16,
                                auditor=RecompileAuditor(),
                                arm_auditor_after_warmup=True,
                                weight_version=prov)
            if engines is not None:
                engines[i] = eng
            return eng

        return LocalReplica(build)

    return ServingCluster(factory, n, supervisor_kwargs=SUP,
                          registry=MetricsRegistry())


# -- publish-directory contract (no cluster, fast) ----------------------------

def test_publish_dir_contract(tmp_path):
    d = str(tmp_path / "pub")
    tree = {"params": {"w": np.ones((3, 2), np.float32)}}
    m1 = publish_weights(d, tree, meta={"step": 10, "loss": 0.5})
    assert m1["version"] == 1 and m1["digest"]
    # The manifest points at a stamped file whose own provenance agrees.
    got = read_manifest(d)
    assert got["version"] == 1 and got["step"] == 10 and got["loss"] == 0.5
    assert os.path.isabs(got["path"]) and os.path.exists(got["path"])
    prov = weights_provenance(got["path"])
    assert prov["version"] == 1 and prov["digest"] == m1["digest"]
    # Versions are monotonic, files immutable-per-version, retention
    # bounded with the manifest's file always kept.
    for _ in range(5):
        publish_weights(d, tree, keep=3)
    names = sorted(n for n in os.listdir(d) if n.startswith("weights-v"))
    assert len(names) == 3
    assert os.path.basename(read_manifest(d)["path"]) == names[-1]
    # A torn/garbage manifest reads as None, not an exception.
    (tmp_path / "pub" / "MANIFEST.json").write_text("{not json")
    assert read_manifest(d) is None

    # Cadence parsing + the loss gate.
    assert parse_publish_every("2.5s").every_seconds == 2.5
    assert parse_publish_every("40").every_steps == 40
    with pytest.raises(ValueError):
        parse_publish_every("0")
    pub = WeightPublisher(str(tmp_path / "gated"),
                          PublishPolicy(every_steps=1,
                                        min_improvement=0.1))
    assert pub.maybe_publish(lambda: tree, step=0, loss_fn=lambda: 1.0)
    assert pub.maybe_publish(lambda: tree, step=1,
                             loss_fn=lambda: 0.99) is None  # not enough
    assert pub.maybe_publish(lambda: tree, step=2, loss_fn=lambda: 0.8)
    assert pub.published == 2


# -- the loop: served provenance flips under load -----------------------------

def test_deploy_loop_flips_served_versions_under_load(lm, rng, tmp_path,
                                                      artifact_dir):
    """Publish two successive good versions while a cluster serves
    continuous load: each deploy canary-validates and rolls with zero
    client-visible errors, every done line names the version that served
    it (boot v1 -> v2 -> v3 in completion order), the rolling reply
    carries per-replica before/after stamps, and the armed auditor
    proves zero decode retraces across both rolls."""
    model, variables = lm
    d = str(tmp_path / "pub")
    boot = publish_weights(d, variables, meta={"step": 0})
    pool = [rng.integers(0, VOCAB, size=(n,)).tolist() for n in (4, 6, 5)]

    async def go():
        engines = {}
        cluster = _cluster(lm, boot["path"], engines)
        completions = []
        errors = []
        stop = asyncio.Event()

        async def worker(k):
            async with ServingClient("127.0.0.1", cluster.port) as c:
                while not stop.is_set():
                    p = pool[(k + len(completions)) % len(pool)]
                    try:
                        done = await c.generate(p, 4)
                        completions.append(
                            (time.monotonic(), done["weight_version"]))
                    except Exception as e:  # any client-visible failure
                        errors.append(repr(e))
                        return

        cluster_ctx = cluster
        async with cluster_ctx:
            registry = cluster.router.registry
            ctrl = wire_controller(
                cluster.router, d, model=model, vocab=VOCAB,
                golden_count=2, golden_len=6, seed=0, registry=registry,
                initial_weights=boot["path"])
            workers = [asyncio.create_task(worker(k)) for k in range(3)]
            while len(completions) < 3:
                await asyncio.sleep(0.02)
            outcomes = []
            for seed in (1, 2):
                fresh = await asyncio.to_thread(model.init, seed)
                await _publish(d, fresh, step=seed * 100, loss=1.0 / seed)
                outcomes.append(await ctrl.poll_once())
                n_after = len(completions) + 3
                while len(completions) < n_after:
                    await asyncio.sleep(0.02)
            stop.set()
            await asyncio.gather(*workers)
            async with ServingClient("127.0.0.1", cluster.port) as c:
                dz = await c.deployz()
            audits = {
                i: (eng.auditor.compiles("serving_decode"),
                    eng.auditor.report()["serving_decode"]["armed"])
                for i, eng in engines.items()}
            # Post-deploy restarts rejoin on the DEPLOYED version: the
            # roll moved current_weights to the controller's staged v3.
            assert (cluster.supervisor.current_weights
                    == dz["current"]["path"])
        return outcomes, completions, errors, dz, audits

    outcomes, completions, errors, dz, audits = asyncio.run(go())
    assert errors == []
    assert [o["status"] for o in outcomes] == ["deployed", "deployed"]
    # Provenance flips in completion order: boot v1 first, every
    # deployed version observed, newest version at the end. (Strict
    # global monotonicity is NOT asserted: during a roll the draining
    # replica's old-version completions legitimately interleave with
    # the first rolled replica's new-version ones.)
    versions = [wv["version"] for _, wv in completions]
    assert sorted(set(versions)) == [1, 2, 3]
    assert versions[0] == 1 and versions[-1] == 3
    for _, wv in completions:
        assert set(wv) == {"version", "digest"} and wv["digest"]
    # The rolling reply's per-replica before/after stamps, recorded in
    # each deploy's history entry: the v3 roll moved every replica
    # v2 -> v3 (the canary replica's "before" may already read v3 —
    # its swap happened in the canary stage).
    assert dz["counters"]["deploys"] == 2
    assert dz["current"]["version"] == 3
    assert [e["status"] for e in dz["history"]] == ["deployed", "deployed"]
    moved = dz["history"][-1]["replicas_moved"]
    assert set(moved) == {"r0", "r1"}
    canary_rid = dz["history"][-1]["canary"]
    for rid, mv in moved.items():
        want_before = 3 if rid == canary_rid else 2
        assert mv["before"]["version"] == want_before, (rid, mv)
        assert mv["after"]["version"] == 3, (rid, mv)
    # Zero retraces across boot + two canaries + two rolls + one direct
    # roll, with the auditor armed the whole time.
    assert audits and all(c == 1 and armed
                          for c, armed in audits.values()), audits
    # The human page renders the same state (run.py deployz's formatter).
    from distkeras_tpu.serving.debugz import format_deployz

    page = format_deployz(dz)
    assert "current:   v3" in page and "deploys=2" in page
    assert "history (most recent last):" in page
    with open(os.path.join(str(artifact_dir), "deployz_snapshot.json"),
              "w") as f:
        json.dump(dz, f, indent=1)


# -- bad candidates: rejected at the right stage, fleet protected -------------

def test_bad_publishes_rejected_and_fleet_protected(lm, rng, tmp_path):
    """Three failure modes through one live cluster: wrong-shaped
    weights fail host-side validation (no replica touched), NaN weights
    fail the canary's finite golden loss, and a latency-budget breach
    fails the replica-side canary and RESTORES the canary replica — the
    fleet serves the last-good version untouched throughout, every
    reject is quarantined with a reason, and a subsequent good publish
    deploys cleanly (the loop is not wedged by failures)."""
    model, variables = lm
    d = str(tmp_path / "pub")
    boot = publish_weights(d, variables)
    import jax

    async def go():
        engines = {}
        cluster = _cluster(lm, boot["path"], engines)
        async with cluster:
            ctrl = wire_controller(
                cluster.router, d, model=model, vocab=VOCAB,
                golden_count=1, golden_len=5, seed=0,
                registry=cluster.router.registry,
                initial_weights=boot["path"])

            # (a) shape mismatch -> validation_failed, before any canary.
            wrong = await asyncio.to_thread(
                lambda: gpt_tiny(seq_len=32, vocab_size=32).init(0))
            await _publish(d, wrong)
            out_a = await ctrl.poll_once()

            # (b) NaN weights -> canary rejects on non-finite golden
            # loss (shape/dtype validation passes by construction).
            bad = await asyncio.to_thread(
                lambda: jax.tree.map(lambda x: np.asarray(x) * np.nan,
                                     model.init(3)))
            await _publish(d, bad)
            out_b = await ctrl.poll_once()

            # (c) impossible latency budget -> replica-side canary
            # fails AFTER the canary replica swapped; it must be
            # restored to last-good and readmitted.
            ctrl.canary_latency_s = 1e-6
            await _publish(d, await asyncio.to_thread(model.init, 4),
                           step=400)
            out_c = await ctrl.poll_once()
            ctrl.canary_latency_s = 30.0

            # Fleet still serves the BOOT version after all three.
            async with ServingClient("127.0.0.1", cluster.port) as c:
                done = await c.generate(
                    rng.integers(0, VOCAB, size=(5,)).tolist(), 4)
                health = await c.healthz()
            # (d) the loop recovers: the next good publish deploys.
            await _publish(d, await asyncio.to_thread(model.init, 5),
                           step=500)
            out_d = await ctrl.poll_once()
            async with ServingClient("127.0.0.1", cluster.port) as c:
                dz = await c.deployz()
            audits = {i: eng.auditor.compiles("serving_decode")
                      for i, eng in engines.items()}
        return out_a, out_b, out_c, done, health, out_d, dz, audits

    out_a, out_b, out_c, done, health, out_d, dz, audits = asyncio.run(go())
    assert out_a["status"] == "validation_failed"
    assert "leaf" in out_a["reason"] or "leaves" in out_a["reason"]
    assert "canary" not in out_a  # no replica was drained for it
    assert out_b["status"] == "canary_rejected"
    assert "not finite" in out_b["reason"]
    assert out_c["status"] == "canary_rejected"
    assert "latency budget" in out_c["reason"]
    # After the three rejects the fleet is whole, single-version, on
    # the boot stamp.
    assert done["weight_version"]["version"] == 1
    assert health["router"]["replicas_ready"] == 2
    assert health["router"]["mixed_weight_versions"] is False
    assert list(health["router"]["weight_versions"].values()) == [2]
    # Recovery deploy landed.
    assert out_d["status"] == "deployed"
    assert dz["current"]["version"] == 5
    # Every reject left a quarantine record (file moved + reason).
    assert {q["version"] for q in dz["quarantined"]} == {2, 3, 4}
    for q in dz["quarantined"]:
        assert q.get("quarantined_to") and os.path.exists(
            q["quarantined_to"])
        assert os.path.exists(q["quarantined_to"] + ".reason.json")
    assert dz["counters"] == {"deploys": 1, "canary_failures": 2,
                              "validation_failures": 1, "rollbacks": 0}
    # Zero retraces through every reject/restore/deploy.
    assert all(c == 1 for c in audits.values()), audits


# -- trainer-side publishing --------------------------------------------------

def test_step_trainer_publishes_on_cadence(tmp_path, rng):
    """SingleTrainer + WeightPublisher: per-step cadence publishes land
    with step/loss metadata and the manifest tracks the newest."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.mlp import higgs_mlp
    from distkeras_tpu.training.trainers import SingleTrainer

    x = rng.normal(size=(96, 28)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = Dataset.from_arrays(features=x, label=y)
    trainer = SingleTrainer(higgs_mlp(), worker_optimizer="adam",
                            learning_rate=0.01, batch_size=32, num_epoch=2)
    d = str(tmp_path / "pub")
    trainer.publisher = WeightPublisher(d, PublishPolicy(every_steps=2))
    trainer.train(ds)
    # 6 steps, cadence 2, first always due: steps 1, 3, 5.
    manifest = read_manifest(d)
    assert manifest["version"] == 3
    assert manifest["step"] == 5
    assert np.isfinite(manifest["loss"])
    # The published file is a servable, stamped weights file.
    v, prov = load_weights_file_with_provenance(manifest["path"])
    assert prov["version"] == 3 and prov["digest"] == manifest["digest"]
    assert "params" in v


@pytest.mark.slow
def test_train_publish_deploy_e2e_real_processes(tmp_path, rng,
                                                 artifact_dir):
    """THE loop on real child processes: a `run.py deploy` child (2
    ProcessReplica serving children + router + controller) watches a
    publish directory; a `run.py train` child (DOWNPOUR, gpt_tiny on
    token data) publishes the PS center on a wall-clock cadence. The
    served weight version flips under client load as deploys land; a
    deliberately corrupted publish is canary-rejected, quarantined, and
    the fleet keeps serving the last-good version; every replica's
    decode step compiled exactly once through all of it."""
    import subprocess
    import sys

    SEQ = 32
    d = str(tmp_path / "pub")

    # Token LM data (the char_lm shape: next-token targets).
    ids = rng.integers(0, VOCAB, size=(3000,)).astype(np.int32)
    starts = np.arange(0, len(ids) - SEQ - 1, 4)
    data = tmp_path / "tokens.npz"
    np.savez(data,
             features=np.stack([ids[s:s + SEQ] for s in starts]),
             label=np.stack([ids[s + 1:s + SEQ + 1] for s in starts]))
    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "trainer": "DOWNPOUR", "worker_optimizer": "adam",
        "learning_rate": 1e-3, "num_workers": 2, "batch_size": 8,
        "num_epoch": 2, "communication_window": 4,
    }))
    model_args = json.dumps({"seq_len": SEQ, "vocab_size": VOCAB})

    deploy_child = subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.run", "deploy",
         "--watch-dir", d, "--model", "gpt_tiny",
         "--model-args", model_args, "--replicas", "2", "--port", "0",
         "--poll-ms", "200", "--golden", "2", "--golden-len", "6",
         "--canary-latency-ms", "60000"],
        stdout=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    train_child = None
    try:
        # Banner lines: bootstrap publish, then the fleet banner (after
        # both replica children answered healthz).
        port = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            line = deploy_child.stdout.readline()
            assert line, "deploy child exited before its banner"
            rec = json.loads(line)
            if "deploy" in rec:
                port = rec["port"]
                break
        assert port, "no deploy banner within 300s"
        assert read_manifest(d)["version"] == 1  # bootstrap publish

        train_child = subprocess.Popen(
            [sys.executable, "-m", "distkeras_tpu.run", "train",
             "--config", str(cfg), "--data", str(data),
             "--model", "gpt_tiny", "--model-args", model_args,
             "--publish-dir", d, "--publish-every", "3s"],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

        seen: list = []

        async def drive():
            async with ServingClient("127.0.0.1", port) as c:
                # Load until the trainer has exited AND its final
                # publish has been deployed (or 300s passes).
                stop_at = time.monotonic() + 300
                while time.monotonic() < stop_at:
                    done = await c.generate(
                        rng.integers(0, VOCAB, size=(5,)).tolist(), 3)
                    seen.append(done["weight_version"])
                    if train_child.poll() is not None:
                        dz = await c.deployz()
                        final = read_manifest(d)["version"]
                        if (dz["counters"]["deploys"] >= 1
                                and dz["seen_version"] >= final):
                            break
                    await asyncio.sleep(0.1)
                # Corrupt publish AFTER training: NaN weights must be
                # canary-rejected without disturbing the fleet.
                model = gpt_tiny(seq_len=SEQ, vocab_size=VOCAB)
                import jax

                publish_weights(d, jax.tree.map(
                    lambda x: np.asarray(x) * np.nan, model.init(9)))
                stop_at = time.monotonic() + 120
                while time.monotonic() < stop_at:
                    dz = await c.deployz()
                    if dz["counters"]["canary_failures"] >= 1:
                        break
                    await asyncio.sleep(0.2)
                done = await c.generate([1, 2, 3], 3)
                health = await c.healthz()
                return dz, done, health

        dz, done, health = asyncio.run(drive())
    finally:
        for child in (train_child, deploy_child):
            if child is not None and child.poll() is None:
                child.terminate()
                try:
                    child.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    child.kill()

    assert train_child.wait() == 0
    # The served version flipped under load: boot v1 plus >= 1 deployed
    # trainer publish observed on done lines.
    versions = sorted({wv["version"] for wv in seen})
    assert versions[0] == 1 and len(versions) >= 2, versions
    assert dz["counters"]["deploys"] >= 1
    # The corrupted publish was rejected + quarantined; the fleet still
    # serves the last-good (deployed) version.
    assert dz["counters"]["canary_failures"] >= 1
    assert dz["quarantined"] and "finite" in dz["quarantined"][-1]["reason"]
    assert done["weight_version"]["version"] == dz["current"]["version"]
    # Fleet whole, single-version, and ZERO decode retraces per replica
    # across boot + every canary + every roll.
    assert health["router"]["replicas_ready"] == 2
    assert health["router"]["mixed_weight_versions"] is False
    for rid, entry in health["replicas"].items():
        assert entry["healthz"]["decode_compile_count"] == 1, (rid, entry)
    with open(os.path.join(str(artifact_dir), "deploy_e2e_deployz.json"),
              "w") as f:
        json.dump(dz, f, indent=1)


def test_async_trainer_publishes_ps_center(tmp_path, rng):
    """DOWNPOUR + publisher thread: the PS center is published on a
    wall-clock cadence during training plus a final snapshot, stamped
    with the commit counter as the step."""
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models.mlp import higgs_mlp
    from distkeras_tpu.training.trainers import DOWNPOUR

    x = rng.normal(size=(256, 28)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = Dataset.from_arrays(features=x, label=y)
    trainer = DOWNPOUR(higgs_mlp(), worker_optimizer="adam",
                       learning_rate=0.01, num_workers=2, batch_size=16,
                       num_epoch=3, communication_window=4)
    d = str(tmp_path / "pub")
    trainer.publisher = WeightPublisher(d, PublishPolicy(every_seconds=0.3))
    trainer.train(ds)
    manifest = read_manifest(d)
    # At least the thread's first publish + the final center snapshot.
    assert manifest["version"] >= 2
    assert manifest["step"] == trainer.parameter_server.num_commits
    assert trainer.publisher.failures == 0
    v, _ = load_weights_file_with_provenance(manifest["path"])
    assert "params" in v
