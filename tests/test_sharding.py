"""Mesh / GSPMD sharding tests on the 8-device CPU mesh."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distkeras_tpu.parallel.mesh import best_mesh, data_parallel_shardings, make_mesh


def test_make_mesh_default_dp():
    mesh = make_mesh()
    assert mesh.axis_names == ("dp",)
    assert mesh.devices.size == 8


def test_make_mesh_dp_tp():
    mesh = make_mesh({"dp": 2, "tp": 4})
    assert set(mesh.axis_names) == {"dp", "tp"}
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4


def test_make_mesh_remainder_folds_into_dp():
    mesh = make_mesh({"tp": 2})
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


def test_make_mesh_bad_sizes():
    with pytest.raises(ValueError):
        make_mesh({"dp": 3, "tp": 3})


def test_best_mesh_too_many_devices_raises():
    with pytest.raises(ValueError):
        best_mesh(16)


def test_data_parallel_shardings_split_batch():
    mesh = best_mesh()
    batch_sh, repl = data_parallel_shardings(mesh)
    x = np.zeros((16, 4), np.float32)
    arr = jax.device_put(x, batch_sh)
    # each device holds 16/8 = 2 rows
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(2, 4)}
    w = jax.device_put(np.zeros((4, 4), np.float32), repl)
    assert {s.data.shape for s in w.addressable_shards} == {(4, 4)}


@pytest.mark.slow
def test_gspmd_bert_params_tp_sharded():
    from distkeras_tpu.models.bert import bert_tiny_mlm
    from distkeras_tpu.ops.losses import get_optimizer
    from distkeras_tpu.parallel.gspmd import (
        batch_sharding,
        make_sharded_train_step,
        sharded_train_state,
    )

    mesh = make_mesh({"dp": 2, "tp": 4})
    model = bert_tiny_mlm(seq_len=16, vocab_size=128)
    opt = get_optimizer("adam", 1e-3)
    state, shardings = sharded_train_state(model, opt, mesh, rng=0)

    mlp_kernel = state.params["layer_0"]["mlp_in"]["kernel"]
    # [hidden=128, mlp=512] sharded over tp=4 on the mlp dim
    assert {s.data.shape for s in mlp_kernel.addressable_shards} == {(128, 128)}

    step = make_sharded_train_step(model, opt, "categorical_crossentropy", mesh)
    rng = np.random.default_rng(0)
    sh = batch_sharding(mesh, 2, seq_dim=None)
    batch = {
        "features": jax.device_put(
            rng.integers(0, 128, size=(8, 16)).astype(np.int32), sh
        ),
        "label": jax.device_put(
            rng.integers(0, 128, size=(8, 16)).astype(np.int32), sh
        ),
    }
    state2, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    # params keep their sharding through the step
    k2 = state2.params["layer_0"]["mlp_in"]["kernel"]
    assert {s.data.shape for s in k2.addressable_shards} == {(128, 128)}


def test_gspmd_loss_matches_single_device():
    """Same init, same batch: sharded step loss == unsharded step loss."""
    from distkeras_tpu.models.bert import bert_tiny_mlm
    from distkeras_tpu.ops.losses import get_optimizer
    from distkeras_tpu.parallel.gspmd import (
        batch_sharding,
        make_sharded_train_step,
        sharded_train_state,
    )
    from distkeras_tpu.training.step import TrainState, make_train_step

    # dropout_rate=0: dropout masks are the one train-step computation
    # whose random bits legitimately differ between sharded and
    # unsharded lowerings on jax 0.4.x (legacy threefry generates
    # different bits once GSPMD shards the mask op, ~1e-3 relative on
    # this loss) — zeroing it makes the parity check deterministic.
    model = bert_tiny_mlm(seq_len=8, vocab_size=64, dropout_rate=0.0)
    opt = get_optimizer("sgd", 0.1)
    rng = np.random.default_rng(1)
    feats = rng.integers(0, 64, size=(4, 8)).astype(np.int32)
    labels = rng.integers(0, 64, size=(4, 8)).astype(np.int32)

    # single-device
    s1 = TrainState.create(model, opt, rng=0)
    step1 = make_train_step(model, opt, "categorical_crossentropy", metrics=(), donate=False)
    _, m1 = step1(s1, {"features": feats, "label": labels})

    # sharded (dp=4, tp=2)
    mesh = make_mesh({"dp": 4, "tp": 2})
    s2, _ = sharded_train_state(model, opt, mesh, rng=0)
    step2 = make_sharded_train_step(model, opt, "categorical_crossentropy", mesh, donate=False)
    sh = batch_sharding(mesh, 2, seq_dim=None)
    _, m2 = step2(
        s2,
        {"features": jax.device_put(feats, sh), "label": jax.device_put(labels, sh)},
    )
    # Tight bound: with dropout off the computation is deterministic, so
    # any layout-dependent divergence is a real bug (the sharded-init
    # divergence fixed in parallel/gspmd.py was ~7e-3 relative here).
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=2e-4)


@pytest.mark.slow
def test_sync_trainer_with_model_sharding():
    """SynchronousDistributedTrainer on a dp x tp mesh trains BERT-tiny with
    data+model sharding (BASELINE config #5 shape)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import bert_tiny_mlm

    rng = np.random.default_rng(0)
    vocab, seq = 64, 8
    feats = rng.integers(0, vocab, size=(256, seq)).astype(np.int32)
    labels = feats.copy()  # trivial denoising target
    ds_mod = __import__("distkeras_tpu.data.dataset", fromlist=["Dataset"])
    ds = ds_mod.Dataset.from_arrays(features=feats, label=labels)

    mesh = make_mesh({"dp": 4, "tp": 2})
    trainer = dk.SynchronousDistributedTrainer(
        bert_tiny_mlm(seq_len=seq, vocab_size=vocab),
        worker_optimizer="adam", learning_rate=1e-3,
        batch_size=8, num_epoch=2, mesh=mesh,
    )
    trained = trainer.train(ds)
    hist = trainer.get_history()
    assert len(hist) > 0
    # loss should drop on the trivial copy task
    assert hist[-1]["loss"] < hist[0]["loss"]


def test_fsdp_params_sharded_and_loss_matches():
    """FSDP heuristic: un-annotated MLP on an fsdp mesh — params sharded,
    loss identical to single-device."""
    from distkeras_tpu.models.core import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.ops.losses import get_optimizer
    from distkeras_tpu.parallel.gspmd import (
        batch_sharding,
        make_sharded_train_step,
        sharded_train_state,
    )
    from distkeras_tpu.training.step import TrainState, make_train_step
    import jax.numpy as jnp

    model = Model.from_flax(
        MLP(features=(256, 256), num_classes=4, compute_dtype=jnp.float32),
        input_shape=(64,),
    )
    opt = get_optimizer("sgd", 0.1)
    mesh = make_mesh({"fsdp": 8})
    state, shardings = sharded_train_state(model, opt, mesh, rng=0)
    k = state.params["Dense_0"]["kernel"]  # [64, 256] -> sharded 256/8
    assert {s.data.shape for s in k.addressable_shards} == {(64, 32)}
    # bias [256] small -> replicated
    b = state.params["Dense_0"]["bias"]
    assert {s.data.shape for s in b.addressable_shards} == {(256,)}

    rng = np.random.default_rng(0)
    feats = rng.normal(size=(16, 64)).astype(np.float32)
    labels = rng.integers(0, 4, size=16).astype(np.float32)
    from distkeras_tpu.parallel.gspmd import shard_batch

    step = make_sharded_train_step(model, opt, "categorical_crossentropy", mesh, donate=False)
    _, m_fsdp = step(state, shard_batch(mesh, {"features": feats, "label": labels}))

    s1 = TrainState.create(model, opt, rng=0)
    plain = make_train_step(model, opt, "categorical_crossentropy", metrics=(), donate=False)
    _, m_plain = plain(s1, {"features": feats, "label": labels})
    np.testing.assert_allclose(float(m_fsdp["loss"]), float(m_plain["loss"]), rtol=2e-5)


def test_zero1_optimizer_state_sharded():
    """ZeRO-1: adam moments shard over dp while params stay replicated;
    the step still computes the same loss."""
    import jax.numpy as jnp
    from distkeras_tpu.models.core import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.ops.losses import get_optimizer
    from distkeras_tpu.parallel.gspmd import (
        make_sharded_train_step,
        shard_batch,
        sharded_train_state,
    )

    model = Model.from_flax(
        MLP(features=(256,), num_classes=4, compute_dtype=jnp.float32),
        input_shape=(64,),
    )
    opt = get_optimizer("adam", 1e-3)
    mesh = make_mesh({"dp": 8})
    state, _ = sharded_train_state(model, opt, mesh, rng=0, zero1=True)
    # params replicated
    k = state.params["Dense_0"]["kernel"]
    assert {s.data.shape for s in k.addressable_shards} == {(64, 256)}
    # adam mu for that kernel sharded over dp=8
    mu_kernel = state.opt_state[0].mu["Dense_0"]["kernel"]
    assert {s.data.shape for s in mu_kernel.addressable_shards} == {(64, 32)}

    step = make_sharded_train_step(model, opt, "categorical_crossentropy", mesh,
                                   donate=False)
    rng = np.random.default_rng(0)
    batch = shard_batch(mesh, {
        "features": rng.normal(size=(16, 64)).astype(np.float32),
        "label": rng.integers(0, 4, size=16).astype(np.float32),
    })
    s2, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))
    # moments keep their dp sharding through the step
    mu2 = s2.opt_state[0].mu["Dense_0"]["kernel"]
    assert {s.data.shape for s in mu2.addressable_shards} == {(64, 32)}


@pytest.mark.slow
def test_sync_trainer_sequence_sharded_bert():
    """BERT-tiny with the sequence dimension sharded over sp (XLA-SP)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import bert_tiny_mlm

    rng = np.random.default_rng(0)
    vocab, seq = 64, 16
    feats = rng.integers(0, vocab, size=(128, seq)).astype(np.int32)
    from distkeras_tpu.data.dataset import Dataset as DS

    ds = DS.from_arrays(features=feats, label=feats)
    mesh = make_mesh({"dp": 2, "sp": 4})
    trainer = dk.SynchronousDistributedTrainer(
        bert_tiny_mlm(seq_len=seq, vocab_size=vocab),
        worker_optimizer="adam", learning_rate=1e-3,
        batch_size=8, num_epoch=2, mesh=mesh, shard_sequence=True,
    )
    trainer.train(ds)
    hist = trainer.get_history()
    assert hist[-1]["loss"] < hist[0]["loss"]
