"""Multi-replica serving cluster (distkeras_tpu.serving.cluster).

In-process replicas (LocalReplica: one engine + server per replica on
ephemeral ports, all on one event loop) so the cluster invariants run on
CPU in seconds. Under test:

- the router speaks the single-server wire protocol: streams route
  through it with greedy parity against generate(), and healthz/metricsz
  aggregate the fleet;
- prefix-cache affinity pins a prompt family to one replica;
- THE chaos invariant: SIGKILL-equivalent replica death under concurrent
  load loses no zero-streamed request (retried on the survivor), the
  supervisor restarts the corpse with backoff, and it rejoins routing;
- zero-downtime rolling reload: under continuous load, a reload verb
  swaps weights one replica at a time with no client-visible error,
  completions keep flowing DURING the roll, outputs are token-identical
  to generate() under the matching weights, and each replica's armed
  RecompileAuditor proves the decode step never retraced (compile==1);
- bad weights are rejected loudly and the fleet keeps serving the old
  params.
"""

import asyncio
import time

import numpy as np
import pytest

from distkeras_tpu.checkpoint import save_weights_file
from distkeras_tpu.inference.generate import generate
from distkeras_tpu.models.bert import gpt_tiny
from distkeras_tpu.serving import (
    LocalReplica,
    ServingClient,
    ServingCluster,
    ServingEngine,
)
from distkeras_tpu.serving.client import ServerError
from distkeras_tpu.telemetry import MetricsRegistry, RecompileAuditor

VOCAB = 64

# Fast-failure supervisor settings for tests: probe often, restart fast.
SUP = dict(health_interval_s=0.05, health_timeout_s=2.0, fail_after=2,
           base_delay_s=0.05, max_delay_s=1.0, stable_after_s=0.5)


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(0)


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


def _want(lm_pair, prompt, n, variables=None):
    model, default_vars = lm_pair
    return generate(model, variables or default_vars,
                    np.asarray([prompt], np.int32), n,
                    greedy=True)[0].tolist()


def _factory(lm_pair, engines=None, audit=False, **engine_kwargs):
    """Replica factory over a shared (model, variables); ``engines`` (a
    dict) collects live engines by replica index for invariant checks.
    ``audit=True`` gives each engine its OWN armed RecompileAuditor
    (sharing one across replicas would double-count compiles)."""
    model, variables = lm_pair

    def make(i):
        def build():
            kw = dict(engine_kwargs)
            if audit:
                kw.update(auditor=RecompileAuditor(),
                          arm_auditor_after_warmup=True)
            eng = ServingEngine(model, variables, slots=2, max_queue=16,
                                **kw)
            if engines is not None:
                engines[i] = eng
            return eng

        return LocalReplica(build)

    return make


async def _wait_until(cond, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        await asyncio.sleep(0.02)


# -- routing + aggregation ----------------------------------------------------

def test_router_parity_and_fleet_aggregation(lm, rng):
    prompts = [_prompt(rng, n) for n in (5, 9, 3, 7)]

    async def go():
        cluster = ServingCluster(_factory(lm), 2, supervisor_kwargs=SUP,
                                 registry=MetricsRegistry())
        async with cluster:
            async def one(p):
                async with ServingClient("127.0.0.1", cluster.port) as c:
                    return (await c.generate(p, 6))["tokens"]

            outs = await asyncio.gather(*(one(p) for p in prompts))
            async with ServingClient("127.0.0.1", cluster.port) as c:
                health = await c.healthz()
                metrics = await c.metricsz()
            return outs, health, metrics, cluster

    outs, health, metrics, cluster = asyncio.run(go())
    for p, got in zip(prompts, outs):
        assert got == _want(lm, p, 6)
    assert health["router"]["replicas_ready"] == 2
    assert health["router"]["outstanding_total"] == 0
    assert set(health["replicas"]) == {"r0", "r1"}
    for entry in health["replicas"].values():
        assert entry["healthz"]["slots"] == 2  # per-replica healthz rode up
    # Per-replica metric snapshots aggregate under replica ids, and the
    # whole fleet together completed every request exactly once.
    done = sum(
        snap["serving_requests_completed_total"]["value"]
        for snap in metrics["replicas"].values())
    assert done == len(prompts)
    assert metrics["router"]["router_requests_total"]["value"] == len(prompts)


def test_fleet_telemetry_push_and_sloz_end_to_end(lm, rng):
    """The pushed-metrics plane, end to end: replicas stream metric
    deltas to the router on a cadence (mux ``telemetry_start``
    subscriptions, one per replica), the router folds them into
    fleet-merged histograms whose count covers every served request,
    the Prometheus page carries both the per-replica and the
    ``fleet="all"`` series, and ``sloz``/``healthz`` serve the burn-rate
    engine's state over the same store."""
    from distkeras_tpu.serving.slo import default_objectives

    prompts = [_prompt(rng, n) for n in (5, 7, 4, 6)]

    async def go():
        cluster = ServingCluster(
            _factory(lm), 2, supervisor_kwargs=SUP,
            # CPU tiny-model fleets legitimately breach production-shaped
            # latency targets during warmup; relaxed thresholds keep the
            # ttft/itl objectives asserting "ok" below.
            router_kwargs={
                "telemetry_interval_s": 0.05,
                "telemetry_window_s": 0.25,
                "slo_objectives": default_objectives(
                    ttft_threshold_s=30.0, itl_threshold_s=30.0),
            })
        async with cluster:
            async def one(p):
                async with ServingClient("127.0.0.1", cluster.port) as c:
                    return (await c.generate(p, 6))["tokens"]

            outs = await asyncio.gather(*(one(p) for p in prompts))
            router = cluster.router
            # Every request produced one TTFT observation; the pushed
            # deltas must converge the fleet merge onto all of them.
            await _wait_until(
                lambda: (router.fleet.fleet_hist_state(
                    "serving_ttft_seconds") or {}).get("count", 0)
                >= len(prompts),
                timeout=20.0, what="fleet-merged TTFT to cover all "
                                   "requests")
            async with ServingClient("127.0.0.1", cluster.port) as c:
                health = await c.healthz()
                sloz = (await c._control({"cmd": "sloz"}))["sloz"]
                prom = await c.metricsz(format="prometheus")
            fleet_snap = router.fleet.registry.snapshot()
            return outs, health, sloz, prom, fleet_snap

    outs, health, sloz, prom, fleet_snap = asyncio.run(go())
    for p, got in zip(prompts, outs):
        assert got == _want(lm, p, 6)  # telemetry never skews serving
    # healthz folds the plane in: overall SLO state + aggregation stats.
    assert health["router"]["slo"] in ("ok", "warn", "page")
    telem = health["router"]["telemetry"]
    assert telem["pushes"] > 0 and telem["push_errors"] == 0
    assert telem["push_subscriptions"] == 2  # both replicas push (mux)
    assert telem["interval_s"] == 0.05
    assert set(telem["replicas"]) == {"r0", "r1"}
    # sloz: the burn-rate snapshot plus the same aggregation rollup.
    assert sloz["aggregation"]["pushes"] >= telem["pushes"]
    assert 0 <= sloz["aggregation"]["staleness_s"] < 5.0
    by_name = {o["objective"]: o for o in sloz["objectives"]}
    assert by_name["ttft_p99"]["state"] == "ok"
    assert by_name["itl_p99"]["state"] == "ok"
    # The fleet Prometheus page renders the merged series both ways.
    assert 'fleet="all"' in prom
    assert "serving_ttft_seconds" in prom
    # Gauges stay per-replica only (summing occupancy ratios is a lie).
    assert 'serving_slot_occupancy{fleet="all"}' not in prom
    for rid in ("r0", "r1"):
        assert any("serving_slot_occupancy" in k and f"replica={rid}" in k
                   for k in fleet_snap)


def test_affinity_pins_prompt_family_to_one_replica(lm, rng):
    family = _prompt(rng, 16)  # >= affinity_tokens: one prompt family

    async def go():
        cluster = ServingCluster(
            _factory(lm), 2, supervisor_kwargs=SUP,
            router_kwargs={"affinity_tokens": 16},
            registry=MetricsRegistry())
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port) as c:
                for _ in range(6):
                    await c.generate(family + _prompt(rng, 2), 4)
                metrics = await c.metricsz()
        return metrics

    metrics = asyncio.run(go())
    completed = sorted(
        snap["serving_requests_completed_total"]["value"]
        for snap in metrics["replicas"].values())
    # Every request in the family landed on the SAME replica (sequential
    # submission: outstanding stayed 0, so the pin never spilled).
    assert completed == [0.0, 6.0]
    assert metrics["router"]["router_affinity_picks_total"]["value"] == 6


# -- chaos: replica death under load ------------------------------------------

def test_replica_death_retries_zero_streamed_and_restarts(lm, rng):
    """THE chaos acceptance test: under concurrent load, hard-kill one
    replica of two. Every request that had streamed zero tokens completes
    via retry on the survivor (token-identical to generate()); only
    mid-stream requests may fail, and with a typed terminal error. The
    supervisor restarts the dead replica and it rejoins routing."""
    prompts = [_prompt(rng, 4 + (i % 5)) for i in range(12)]

    async def go():
        cluster = ServingCluster(_factory(lm), 2, supervisor_kwargs=SUP,
                                 registry=MetricsRegistry())
        results: dict[int, list[int]] = {}
        failures: dict[int, tuple[str, int]] = {}

        async with cluster:
            async def client_task(idx, p):
                streamed = []
                c = ServingClient("127.0.0.1", cluster.port)
                try:
                    done = await c.generate(p, 8, on_token=streamed.append)
                    results[idx] = done["tokens"]
                except (ServerError, ConnectionError) as e:
                    failures[idx] = (str(e), len(streamed))
                finally:
                    await c.aclose()

            tasks = [asyncio.create_task(client_task(i, p))
                     for i, p in enumerate(prompts)]
            # Let the fleet get properly mid-stream, then kill r0 hard.
            await _wait_until(lambda: len(results) >= 2, what="first done")
            await cluster.replicas["r0"].handle.kill()
            await asyncio.gather(*tasks)

            # Supervisor notices (router feedback or health probe),
            # restarts, and the replica rejoins. Wait for the restart
            # ITSELF before waiting on ready_count: when every in-flight
            # request drains before the ~0.1 s probe window closes,
            # ready_count still reads a stale 2 off the not-yet-probed
            # corpse and the restart assertion would race the probe.
            await _wait_until(
                lambda: cluster.replicas["r0"].restarts >= 1,
                what="supervisor restart of r0")
            await _wait_until(
                lambda: cluster.supervisor.ready_count == 2,
                what="replica rejoin")
            assert cluster.replicas["r0"].restarts >= 1

            # The restarted replica serves traffic again: flood enough
            # sequential requests that least-outstanding/affinity sends
            # some its way, and every one completes.
            async with ServingClient("127.0.0.1", cluster.port) as c:
                post = [
                    (p, (await c.generate(p, 4))["tokens"])
                    for p in (_prompt(rng, n) for n in (3, 5, 6, 4, 7, 8))
                ]
        return results, failures, post

    results, failures, post = asyncio.run(go())
    # Zero-streamed requests NEVER fail: every failure streamed >= 1
    # token before its replica died (not idempotent, typed error).
    for idx, (msg, streamed) in failures.items():
        assert streamed >= 1, (
            f"request {idx} failed with zero tokens streamed: {msg}")
    assert len(results) + len(failures) == len(prompts)
    # Survivor-side completions (including retried ones) are exact.
    for idx, got in results.items():
        assert got == _want(lm, prompts[idx], 8)
    for p, got in post:
        assert got == _want(lm, p, 4)


# -- zero-downtime rolling reload ---------------------------------------------

def test_rolling_reload_under_load_zero_downtime(lm, rng, tmp_path):
    """Reload new weights through a loaded 2-replica cluster: no client
    sees an error, completions keep landing DURING the roll (never fewer
    than N-1 replicas serving), post-roll outputs are token-identical to
    generate() under the NEW weights, and each replica's armed auditor
    proves its decode step compiled exactly once across the swap."""
    model, variables = lm
    new_vars = model.init(1)
    weights_path = str(tmp_path / "new_weights.bin")
    save_weights_file(weights_path, new_vars)
    pool = [_prompt(rng, n) for n in (4, 6, 5, 7)]
    want_old = {tuple(p): _want(lm, p, 5) for p in pool}
    want_new = {tuple(p): _want(lm, p, 5, variables=new_vars) for p in pool}

    async def go():
        engines: dict[int, ServingEngine] = {}
        cluster = ServingCluster(
            _factory(lm, engines=engines, audit=True),
            2, supervisor_kwargs=SUP, registry=MetricsRegistry())
        completions: list[tuple[float, tuple, list[int]]] = []
        stop = asyncio.Event()

        async def worker(k):
            async with ServingClient("127.0.0.1", cluster.port) as c:
                while not stop.is_set():
                    p = pool[(k + len(completions)) % len(pool)]
                    done = await c.generate(p, 5)
                    completions.append(
                        (time.monotonic(), tuple(p), done["tokens"],
                         done.get("weight_version")))

        async with cluster:
            workers = [asyncio.create_task(worker(k)) for k in range(3)]
            await _wait_until(lambda: len(completions) >= 4,
                              what="warmup completions")
            t0 = time.monotonic()
            async with ServingClient("127.0.0.1", cluster.port) as c:
                rep = await c.reload(weights_path, timeout=60.0)
            t1 = time.monotonic()
            # A few more completions on the new weights.
            n_after = len(completions) + 4
            await _wait_until(lambda: len(completions) >= n_after,
                              what="post-reload completions")
            stop.set()
            await asyncio.gather(*workers, return_exceptions=False)
            # Drive EACH replica's engine directly post-roll: proves both
            # actually serve the new weights (routing affinity may have
            # starved one of organic traffic) and arms any auditor whose
            # engine had only seen its swap-rewarm tick so far.
            per_replica = {
                i: await eng.submit(pool[0], 5).result()
                for i, eng in engines.items()
            }
            audits = {
                i: (eng.auditor.compiles("serving_decode"),
                    eng.auditor.report()["serving_decode"]["armed"])
                for i, eng in engines.items()
            }
            # A crash AFTER the roll must not resurrect the boot
            # weights: the supervisor brings the fresh replica (whose
            # factory rebuilds with the OLD variables) to the fleet's
            # current weights before readmitting it.
            await cluster.replicas["r0"].handle.kill()
            await _wait_until(lambda: cluster.supervisor.ready_count < 2,
                              what="death detection")
            await _wait_until(lambda: cluster.supervisor.ready_count == 2,
                              what="post-reload restart")
            restarted = await engines[0].submit(pool[0], 5).result()
            # Weight-provenance rollup at the ROUTER: after the roll and
            # the restart (brought to current weights), the fleet must
            # be single-version on the reloaded file's stamp.
            async with ServingClient("127.0.0.1", cluster.port) as c:
                fleet_health = await c.healthz()
        return (rep, completions, t0, t1, audits, per_replica, restarted,
                fleet_health)

    (rep, completions, t0, t1, audits, per_replica,
     restarted, fleet_health) = asyncio.run(go())
    from distkeras_tpu.checkpoint import weights_provenance

    new_prov = weights_provenance(weights_path)
    assert new_prov["version"] == 1 and new_prov["digest"]
    # Per-request provenance: pre-roll requests carry the boot stamp
    # (version 0, inline variables), post-roll requests the reloaded
    # file's version+digest — old vs new visible on every done line.
    for t, p, got, wv in completions:
        assert isinstance(wv, dict), "done line lost weight_version"
        if t < t0:
            assert wv["version"] == 0
        elif t > t1:
            assert wv["version"] == new_prov["version"]
            assert wv["digest"] == new_prov["digest"]
    router_h = fleet_health["router"]
    key = f"{new_prov['version']}:{new_prov['digest']}"
    assert router_h["weight_versions"] == {key: 2}
    assert router_h["mixed_weight_versions"] is False
    assert restarted == want_new[tuple(pool[0])], \
        "restarted replica rejoined on stale boot weights"
    for i, got in per_replica.items():
        assert got == want_new[tuple(pool[0])], f"replica {i} serves stale"
    assert rep["ok"] and sorted(rep["reloaded"]) == ["r0", "r1"]
    assert rep["failed"] == {}
    # No client-visible error: every worker iteration completed (worker
    # exceptions would have propagated from gather).
    # Zero downtime: completions landed INSIDE the reload window.
    during = [c for c in completions if t0 <= c[0] <= t1]
    assert during, "no request completed while the reload was rolling"
    # Token parity: before the roll -> old weights; after it -> new
    # weights; inside the window either (depends which replica served).
    for t, p, got, _wv in completions:
        if t < t0:
            assert got == want_old[p]
        elif t > t1:
            assert got == want_new[p]
        else:
            assert got in (want_old[p], want_new[p])
    # The armed auditor held through the swap on both replicas: exactly
    # one decode executable each, before AND after the param swap.
    for i, (compiles, armed) in audits.items():
        assert compiles == 1 and armed, f"replica {i}: {audits[i]}"


def test_reload_rejects_mismatched_weights_and_keeps_serving(lm, rng,
                                                             tmp_path):
    wrong = gpt_tiny(seq_len=32, vocab_size=32)  # different embed shape
    path = str(tmp_path / "wrong.bin")
    save_weights_file(path, wrong.init(0))
    p = _prompt(rng, 5)

    async def go():
        cluster = ServingCluster(_factory(lm), 2, supervisor_kwargs=SUP,
                                 registry=MetricsRegistry())
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port) as c:
                before = (await c.generate(p, 4))["tokens"]
                rep = await c.reload(path)
                after = (await c.generate(p, 4))["tokens"]
                health = await c.healthz()
        return before, rep, after, health

    before, rep, after, health = asyncio.run(go())
    assert not rep["ok"]
    assert set(rep["failed"]) == {"r0", "r1"}  # both rejected, loudly
    # Old weights kept serving, replicas readmitted.
    assert before == after == _want(lm, p, 4)
    assert health["router"]["replicas_ready"] == 2


# -- engine-level swap unit -----------------------------------------------

def test_engine_param_swap_flushes_prefix_cache_and_is_exact(lm, rng):
    """request_param_swap alone (no cluster): post-swap greedy output is
    token-identical to generate() under the new params, the prefix cache
    is flushed (old-weight K/V must never splice again), and a
    mismatched tree raises before touching engine state."""
    model, variables = lm
    new_vars = model.init(2)
    engine = ServingEngine(model, variables, slots=1, max_queue=8,
                           prefix_cache_mb=1.0, prefix_block_tokens=4)
    shared = _prompt(rng, 9)
    p1, p2 = shared + _prompt(rng, 2), shared + _prompt(rng, 3)

    async def go():
        task = asyncio.create_task(engine.run())
        try:
            out_old = await engine.submit(p1, 4).result()
            assert engine.prefix_cache.blocks_used > 0
            event, result = engine.request_param_swap(new_vars)
            await asyncio.wait_for(event.wait(), 30)
            assert "error" not in result
            assert engine.prefix_cache.blocks_used == 0  # flushed
            assert engine.prefix_cache.stats()["flushes"] == 1
            out_new1 = await engine.submit(p1, 4).result()
            out_new2 = await engine.submit(p2, 4).result()
            return out_old, out_new1, out_new2
        finally:
            engine.shutdown(drain=True)
            await task

    out_old, out_new1, out_new2 = asyncio.run(go())
    assert out_old == _want(lm, p1, 4)
    assert out_new1 == _want(lm, p1, 4, variables=new_vars)
    # Re-cached under the NEW weights, the second hit is still exact.
    assert out_new2 == _want(lm, p2, 4, variables=new_vars)
    assert engine.prefix_cache.stats()["hit_requests"] >= 1
    assert engine.decode_compile_count() in (1, -1)

    with pytest.raises(ValueError, match="leaf|leaves"):
        engine.request_param_swap(
            gpt_tiny(seq_len=32, vocab_size=32).init(0))


# -- process-mode integration (the `run serve --replicas N` shape) ------------

@pytest.mark.slow
def test_process_replica_cluster_end_to_end(lm, rng):
    """Real child processes behind the router — the deployment shape
    `python -m distkeras_tpu.run serve --replicas N` wires up. One
    greedy round trip (parity against the parent's identically-seeded
    weights) plus fleet healthz. Slow lane: each replica pays a full jax
    import + compile."""
    from distkeras_tpu.serving.cluster import ProcessReplica

    p = _prompt(rng, 5)

    async def go():
        extra = ["--model", "gpt_tiny",
                 "--model-args", '{"seq_len": 32, "vocab_size": 64}',
                 "--slots", "2", "--seed", "0"]
        cluster = ServingCluster(lambda i: ProcessReplica(extra), 2,
                                 supervisor_kwargs=dict(
                                     health_interval_s=0.5))
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port) as c:
                done = await c.generate(p, 4)
                health = await c.healthz()
        return done, health

    done, health = asyncio.run(go())
    assert done["tokens"] == _want(lm, p, 4)
    assert health["router"]["replicas_ready"] == 2
