"""Unified telemetry layer (distkeras_tpu.telemetry).

Covers the four pillars:
- spans: no-op when disabled, correct nesting across threads and asyncio
  tasks, Chrome-trace export is valid JSON with matched B/E per lane;
- recompile auditor: counts compiles with triggering shapes, flags an
  intentionally shape-unstable jit when armed, signature fallback when
  the jit cache probe is absent;
- registry: counter/gauge/histogram semantics, shared percentile edge
  cases (empty raises, single sample exact), Prometheus text exposition;
- streams/timers: MetricStream close + context manager, StepTimer tail
  percentiles.
"""

import asyncio
import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import telemetry as T
from distkeras_tpu.telemetry import (
    MetricsRegistry,
    RecompileAuditor,
    RecompileError,
    Tracer,
    percentile,
)


@pytest.fixture(autouse=True)
def _tracing_disabled():
    """Every test starts and ends with tracing off (module-global state)."""
    T.disable_tracing()
    yield
    T.disable_tracing()


def _balanced_stacks(trace: dict) -> dict[int, list[str]]:
    """Walk traceEvents asserting every E matches the innermost B on its
    lane; returns the (empty) final per-lane stacks."""
    stacks: dict[int, list[str]] = {}
    for ev in trace["traceEvents"]:
        if ev["ph"] == "B":
            stacks.setdefault(ev["tid"], []).append(ev["name"])
        elif ev["ph"] == "E":
            lane = stacks.get(ev["tid"])
            assert lane, f"E {ev['name']!r} without B on lane {ev['tid']}"
            assert lane.pop() == ev["name"]
    assert all(not s for s in stacks.values()), f"unclosed spans: {stacks}"
    return stacks


# -- spans --------------------------------------------------------------------

def test_span_disabled_is_noop_singleton():
    a = T.span("x")
    b = T.span("y", attr=1)
    assert a is b  # the shared null span: no allocation on the hot path
    with a:
        pass


def test_spans_nest_and_record_parents():
    tracer = T.enable_tracing()
    with T.span("outer", step=1):
        with T.span("inner"):
            pass
    with T.span("sibling"):
        pass
    T.disable_tracing()
    events = tracer.events()
    names = [(ph, name) for ph, name, *_ in events]
    assert names == [("B", "outer"), ("B", "inner"), ("E", "inner"),
                     ("E", "outer"), ("B", "sibling"), ("E", "sibling")]
    by_name = {name: parent for ph, name, t, lane, parent, attrs in events
               if ph == "B"}
    assert by_name["inner"] == "outer"
    assert by_name["outer"] is None and by_name["sibling"] is None


def test_chrome_trace_valid_json_matched_be(tmp_path):
    tracer = T.enable_tracing()
    with T.span("a"):
        with T.span("b", k=2):
            pass
    T.disable_tracing()
    path = tracer.export_chrome_trace(str(tmp_path / "trace.json"))
    trace = json.loads(open(path).read())
    assert isinstance(trace["traceEvents"], list)
    bs = [e for e in trace["traceEvents"] if e["ph"] == "B"]
    es = [e for e in trace["traceEvents"] if e["ph"] == "E"]
    assert len(bs) == len(es) == 2
    for e in bs + es:
        assert set(e) >= {"name", "ph", "pid", "tid", "ts"}
    _balanced_stacks(trace)
    b_b = next(e for e in bs if e["name"] == "b")
    assert b_b["args"] == {"k": 2, "parent": "a"}


def test_spans_across_asyncio_tasks_get_own_lanes():
    """Two concurrent tasks interleave on one thread; each must land on
    its own lane with stack-balanced B/E, parented to the span that was
    active when the task was created."""
    tracer = T.enable_tracing()

    async def worker(tag):
        with T.span(f"task_{tag}"):
            await asyncio.sleep(0.01)
            with T.span(f"step_{tag}"):
                await asyncio.sleep(0.01)

    async def main():
        with T.span("root"):
            await asyncio.gather(worker("a"), worker("b"))

    asyncio.run(main())
    T.disable_tracing()
    events = tracer.events()
    parents = {name: parent for ph, name, t, lane, parent, _ in events
               if ph == "B"}
    assert parents["task_a"] == "root" and parents["task_b"] == "root"
    assert parents["step_a"] == "task_a" and parents["step_b"] == "task_b"
    lanes = {name: lane for ph, name, t, lane, parent, _ in events
             if ph == "B"}
    assert lanes["task_a"] != lanes["task_b"]  # separate swimlanes
    _balanced_stacks(tracer.chrome_trace())


def test_spans_across_threads_get_own_lanes():
    tracer = T.enable_tracing()

    def work(tag):
        with T.span(f"thread_{tag}"):
            pass

    ts = [threading.Thread(target=work, args=(i,)) for i in range(2)]
    with T.span("main"):
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    T.disable_tracing()
    lanes = {name: lane for ph, name, t, lane, parent, _ in tracer.events()
             if ph == "B"}
    assert len({lanes["main"], lanes["thread_0"], lanes["thread_1"]}) == 3
    _balanced_stacks(tracer.chrome_trace())


def test_tracer_event_cap_keeps_matched_be():
    """A full tracer drops NEW spans whole (counted), while admitted and
    still-open spans keep their closing E — the recorded stream stays
    stack-balanced per lane."""
    tracer = T.enable_tracing(Tracer(max_events=4))
    with T.span("outer"):           # admitted (reserves its E)
        with T.span("kept"):        # admitted: 2 events used + reserve
            pass
        for _ in range(10):         # cap hit: all dropped
            with T.span("dropped"):
                pass
    T.disable_tracing()
    assert tracer.dropped_spans == 10
    names = [name for ph, name, *_ in tracer.events()]
    assert "dropped" not in names
    trace = tracer.chrome_trace()
    _balanced_stacks(trace)
    meta = [e for e in trace["traceEvents"] if e["name"] == "dropped_spans"]
    assert meta and meta[0]["args"]["count"] == 10


def test_sanitize_metric_name():
    from distkeras_tpu.telemetry import sanitize_metric_name

    assert sanitize_metric_name("loss") == "loss"
    assert sanitize_metric_name("weird key!") == "weird_key_"
    assert sanitize_metric_name("1st") == "_1st"
    assert sanitize_metric_name("") == "_"


# -- recompile auditor --------------------------------------------------------

def test_auditor_flags_shape_unstable_jit_when_armed():
    auditor = RecompileAuditor()
    unstable = auditor.wrap(jax.jit(lambda x: x * 2), "unstable")
    unstable(jnp.ones((3,)))
    unstable(jnp.ones((3,)))  # cache hit
    assert auditor.compiles("unstable") == 1
    unstable(jnp.ones((4,)))  # retrace: new shape
    assert auditor.compiles("unstable") == 2
    auditor.arm("unstable")
    unstable(jnp.ones((4,)))  # still cached: fine while armed
    with pytest.raises(RecompileError, match="unstable"):
        unstable(jnp.ones((5,)))
    rep = auditor.report()["unstable"]
    assert rep["compiles"] == 3 and rep["armed"]
    # The triggering abstract shapes are in the record.
    assert any("5" in sig for sig in rep["signatures"])


def test_auditor_signature_fallback_without_probe():
    """A callable with no jit cache probe is audited by abstract input
    signature — distinct shapes count, repeats don't."""
    auditor = RecompileAuditor()
    fn = auditor.wrap(lambda x: np.asarray(x) * 2, "plain")
    fn(np.ones((3,)))
    fn(np.ones((3,)))
    fn(np.ones((2, 2)))
    assert auditor.compiles("plain") == 2


def test_auditor_registry_publishing_and_wrap_uniqueness():
    reg = MetricsRegistry()
    auditor = RecompileAuditor(registry=reg)
    f = auditor.wrap(jax.jit(lambda x: x + 1), "f")
    f(jnp.ones((2,)))
    snap = reg.snapshot()
    assert snap["recompile_auditor_compiles_total{fn=f}"]["value"] == 1.0
    with pytest.raises(ValueError, match="already wraps"):
        auditor.wrap(lambda x: x, "f")


# -- registry -----------------------------------------------------------------

def test_counter_gauge_semantics():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", help="h")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    assert reg.counter("reqs_total") is c  # get-or-create
    with pytest.raises(TypeError):
        reg.gauge("reqs_total")  # kind mismatch is loud
    g = reg.gauge("depth")
    g.set(4)
    g.dec()
    assert g.value == 3


def test_histogram_and_shared_percentile_agree_on_edges():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    with pytest.raises(ValueError):
        h.percentile(50)  # empty
    with pytest.raises(ValueError):
        percentile([], 50)  # the exact helper agrees
    h.observe(0.42)
    assert h.percentile(1) == 0.42 == h.percentile(99)  # single: exact
    assert percentile([0.42], 1) == 0.42 == percentile([0.42], 99)
    for v in (0.02, 0.05, 0.2, 0.7):
        h.observe(v)
    assert h.count == 5 and h.sum == pytest.approx(1.39)
    # Bucket estimate stays within the observed range and brackets p50.
    assert 0.02 <= h.percentile(50) <= 0.7
    # Exact percentile matches numpy's linear interpolation.
    xs = [3.0, 1.0, 2.0, 4.0]
    assert percentile(xs, 50) == pytest.approx(float(np.percentile(xs, 50)))
    assert percentile(xs, 90) == pytest.approx(float(np.percentile(xs, 90)))


def test_prometheus_text_format(tmp_path):
    reg = MetricsRegistry()
    reg.counter("reqs_total", help="requests", code="ok").inc(5)
    reg.gauge("depth").set(2)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = T.prometheus_text(reg)
    assert "# TYPE reqs_total counter" in text
    assert 'reqs_total{code="ok"} 5' in text
    assert "depth 2" in text
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 2' in text
    assert "lat_seconds_count 2" in text
    # JSONL snapshot round-trips.
    path = tmp_path / "m.jsonl"
    T.write_snapshot_jsonl(reg, str(path))
    T.write_snapshot_jsonl(reg, str(path))
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    assert lines[0]["metrics"]["depth"]["value"] == 2


# -- stream close + timer tails ----------------------------------------------

def test_metric_stream_close_and_context_manager(tmp_path):
    from distkeras_tpu.tracing import MetricStream

    path = tmp_path / "m.jsonl"
    ms = MetricStream.to_jsonl(str(path))
    ms.emit(0, {"loss": 1.0})
    assert not ms._files[0].closed
    ms.close()
    ms.close()  # idempotent
    with pytest.raises(ValueError, match="closed"):
        ms.emit(1, {"loss": 0.5})
    with MetricStream.to_jsonl(str(path)) as ms2:
        ms2.emit(1, {"loss": 0.5})
        handle = ms2._files[0]
    assert handle.closed
    assert len([json.loads(l) for l in open(path)]) == 2


def test_metric_stream_publishes_to_registry(tmp_path):
    from distkeras_tpu.tracing import MetricStream

    reg = MetricsRegistry()
    ms = MetricStream(registry=reg)
    ms.emit(0, {"loss": 1.5, "weird key!": 2.0})
    ms.emit(1, {"loss": 1.2})
    snap = reg.snapshot()
    assert snap["stream_records_total"]["value"] == 2
    assert snap["stream_loss"]["value"] == 1.2  # latest value wins
    assert snap["stream_weird_key_"]["value"] == 2.0  # sanitized name


def test_step_timer_tail_percentiles():
    from distkeras_tpu.tracing import StepTimer

    t = StepTimer()
    t.start()
    t._times = [0.01] * 98 + [0.05, 0.1]  # deterministic synthetic tail
    s = t.summary(skip_warmup=0)
    assert s["step_time_p90_s"] == pytest.approx(0.01)
    assert s["step_time_p99_s"] > s["step_time_p90_s"]
    assert s["step_time_p99_s"] <= 0.1


def test_tracing_reexports_canonical_telemetry():
    """tracing.py stays a one-stop import for observability users."""
    from distkeras_tpu import tracing

    assert tracing.span is T.span
    assert tracing.enable_tracing is T.enable_tracing
    assert tracing.Tracer is T.Tracer
