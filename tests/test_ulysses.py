"""Ulysses all-to-all sequence parallelism: forward/gradients verified
against dense attention; e2e BERT training on a dp x sp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.ops.ulysses import ulysses_self_attention
from distkeras_tpu.parallel.mesh import make_mesh


def _qkv(rng, B=2, S=64, H=4, D=8):
    mk = lambda: np.asarray(rng.normal(size=(B, S, H, D)), np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_ulysses_matches_dense(rng, causal):
    q, k, v = _qkv(rng)
    mesh = make_mesh({"dp": 2, "sp": 4})
    out = ulysses_self_attention(q, k, v, mesh, seq_axis="sp", causal=causal)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize(
    "causal",
    [pytest.param(False, marks=pytest.mark.slow),
     pytest.param(True, marks=pytest.mark.slow)],
)
def test_ulysses_gradients_match_dense(rng, causal):
    q, k, v = _qkv(rng, B=1, S=32, H=8, D=8)
    mesh = make_mesh({"sp": 8})

    def loss_u(q, k, v):
        return jnp.mean(
            ulysses_self_attention(q, k, v, mesh, seq_axis="sp",
                                   causal=causal) ** 2
        )

    def loss_dense(q, k, v):
        return jnp.mean(dot_product_attention(q, k, v, causal=causal) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-3)


def test_ulysses_rejects_indivisible_heads(rng):
    q, k, v = _qkv(rng, H=3)
    mesh = make_mesh({"sp": 8})
    with pytest.raises(ValueError, match="num_heads"):
        ulysses_self_attention(q, k, v, mesh, seq_axis="sp")


@pytest.mark.slow
def test_bert_with_ulysses_attention_trains(rng):
    """BERT with Ulysses attention trains under the sync trainer on a
    dp x sp mesh, and its forward matches the plain model's."""
    import dataclasses

    import distkeras_tpu as dk
    from distkeras_tpu.models import bert as bert_mod

    mesh = make_mesh({"dp": 2, "sp": 4})
    vocab, seq = 64, 32
    cfg = bert_mod.BertConfig(
        vocab_size=vocab, hidden_size=64, num_layers=2, num_heads=4,
        mlp_dim=128, max_seq_len=seq, dropout_rate=0.0,
        ring_mesh=mesh, ring_axis="sp", sp_impl="ulysses",
    )
    model = bert_mod._make(cfg, seq, "bert_ulysses")

    tokens = np.asarray(rng.integers(1, vocab, size=(128, seq)), np.int32)
    ds = dk.Dataset.from_arrays(features=tokens, label=tokens)
    trainer = dk.SynchronousDistributedTrainer(
        model, worker_optimizer="adam", learning_rate=1e-3,
        batch_size=8, num_epoch=2, mesh=mesh, shard_sequence=True,
    )
    trainer.train(ds)
    hist = trainer.get_history()
    assert hist[-1]["loss"] < hist[0]["loss"]

    plain_cfg = dataclasses.replace(cfg, ring_mesh=None)
    plain = bert_mod._make(plain_cfg, seq, "bert_plain")
    variables = model.init(3)
    x = tokens[:4]
    o_u, _ = model.apply(variables, x)
    o_plain, _ = plain.apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(o_u), np.asarray(o_plain), atol=3e-2, rtol=3e-2
    )


@pytest.mark.slow  # ~11s: grad parity through the pallas interpreter
def test_ulysses_with_flash_local_matches_dense(rng):
    """Ulysses composed with the Pallas flash kernel as the local attention:
    values and gradients match the dense local default — no O(S^2) local
    scores. (The model-level BertConfig wiring is pinned separately below.)"""
    from distkeras_tpu.ops.pallas.flash_attention import flash_attention

    mesh = make_mesh({"dp": 2, "sp": 4})
    q, k, v = _qkv(rng)

    for causal in (False, True):
        out = ulysses_self_attention(
            q, k, v, mesh, seq_axis="sp", causal=causal,
            attn_fn=flash_attention,
        )
        ref = dot_product_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, rtol=2e-3)

    def loss_flash(q, k, v):
        return jnp.mean(ulysses_self_attention(
            q, k, v, mesh, seq_axis="sp", causal=True,
            attn_fn=flash_attention) ** 2)

    def loss_dense(q, k, v):
        return jnp.mean(dot_product_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3)


@pytest.mark.slow  # two full BERT applies (~17s)
def test_bert_ulysses_flash_model_wiring(rng):
    """BertConfig(sp_impl="ulysses", use_flash_attention=True) dispatches
    to the flash-local composition: logits match the plain dense model on
    identical weights (a typo in the SelfAttention branch cannot hide)."""
    import dataclasses

    from distkeras_tpu.models import bert as bert_mod

    mesh = make_mesh({"dp": 2, "sp": 4})
    vocab, seq = 64, 32
    cfg = bert_mod.BertConfig(
        vocab_size=vocab, hidden_size=64, num_layers=2, num_heads=4,
        mlp_dim=128, max_seq_len=seq, dropout_rate=0.0, causal=True,
        ring_mesh=mesh, ring_axis="sp", sp_impl="ulysses",
        use_flash_attention=True,
    )
    model = bert_mod._make(cfg, seq, "bert_uly_flash")
    plain = bert_mod._make(
        dataclasses.replace(cfg, ring_mesh=None, use_flash_attention=False),
        seq, "bert_uly_plain",
    )
    variables = model.init(3)
    x = np.asarray(rng.integers(1, vocab, size=(4, seq)), np.int32)
    o_sp, _ = model.apply(variables, x)
    o_plain, _ = plain.apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(o_sp), np.asarray(o_plain), atol=3e-2, rtol=3e-2
    )
