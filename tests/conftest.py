"""Test harness: simulate an 8-device TPU mesh on CPU.

Multi-chip hardware is not available in CI; all mesh/pjit/collective code
paths are exercised on 8 virtual CPU devices (SURVEY §4 test-strategy note).
Env vars must be set before jax initializes, hence this file's import-time
side effects.
"""

import os
import re

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("KERAS_BACKEND", "jax")
# The suite is written against 8 virtual devices by default; replace any
# pre-existing count rather than deferring to it. DISTKERAS_FORCE_DEVICES
# overrides the count for lane variants (the CI sharded-serving job runs
# the mesh parity suite on a 4-device host platform; device-count-
# sensitive tests read len(jax.devices()) instead of assuming 8).
_n_devices = int(os.environ.get("DISTKERAS_FORCE_DEVICES", "8"))
_flags = os.environ.get("XLA_FLAGS", "")
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", _flags)
# Single-threaded Eigen: the virtual devices share one intra-op pool,
# and pool-parallel kernels inside collective programs can deadlock the
# all-reduce rendezvous (see utils/platform.ensure_virtual_cpu_flags).
os.environ["XLA_FLAGS"] = (
    _flags + f" --xla_force_host_platform_device_count={_n_devices}"
    " --xla_cpu_multi_thread_eigen=false"
).strip()

# The container's axon sitecustomize force-selects the TPU platform even
# when JAX_PLATFORMS=cpu is in the environment; the config update below is
# what actually pins tests to the virtual CPU devices.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == _n_devices, jax.devices()

# Persistent XLA compilation cache: the suite is compile-dominated (every
# parity test builds prefill/decode executables for the same tiny models),
# so repeat runs on a small CI box spend most of their wall clock
# recompiling programs that haven't changed. Cache keys cover the HLO,
# compile options, and backend, so hits are exact; the RecompileAuditor
# is unaffected (it counts jit-cache entries, which a disk hit still
# creates — only the XLA compile time is skipped). The min-compile-time
# threshold is deliberately left at its default: forcing it to 0 also
# caches sub-second multi-device trainer programs whose round-trip
# through the serializer aborts the process on reload (reproduced on
# tests/test_checkpoint.py). Opt out with DISTKERAS_JAX_CACHE=0;
# override the location (e.g. a CI cache path) with
# DISTKERAS_JAX_CACHE_DIR.
if os.environ.get("DISTKERAS_JAX_CACHE", "1") != "0":
    _cache_dir = os.environ.get(
        "DISTKERAS_JAX_CACHE_DIR",
        os.path.join(os.environ.get("TMPDIR", "/tmp"),
                     "distkeras-jax-cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
    except Exception:
        pass  # older jax without the cache config: run uncached

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def artifact_dir(tmp_path):
    """Where observability artifacts (flight-recorder dumps, metrics
    snapshots, Chrome traces, training-health statusz snapshots) land.
    CI sets DISTKERAS_TEST_ARTIFACTS and uploads the directory when the
    suite fails, so a red serving test ships its black box — and a red
    async-trainer test its statusz worker table — with the failure;
    locally it is just tmp_path. Tests that exercise a multi-worker
    trainer should dump ``trainer.training_health.statusz()`` here
    (see tests/test_training_health.py)."""
    import pathlib

    out = os.environ.get("DISTKERAS_TEST_ARTIFACTS")
    if out:
        path = pathlib.Path(out)
        path.mkdir(parents=True, exist_ok=True)
        return path
    return tmp_path


@pytest.fixture
def toy_classification(rng):
    """Linearly separable 2-class problem: fast convergence sanity checks."""
    from distkeras_tpu.data.dataset import Dataset

    n, d = 512, 16
    w = rng.normal(size=(d,))
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    return Dataset.from_arrays(features=x, label=y)


@pytest.fixture
def toy_multiclass(rng):
    from distkeras_tpu.data.dataset import Dataset

    n, d, c = 768, 20, 4
    centers = rng.normal(size=(c, d)) * 3.0
    labels = rng.integers(0, c, size=n)
    x = (centers[labels] + rng.normal(size=(n, d))).astype(np.float32)
    return Dataset.from_arrays(features=x, label=labels.astype(np.float32))
