"""Job/Punchcard tests (local-execution mode; ssh paths need a cluster)."""

import json

from distkeras_tpu.deployment import Job, Punchcard


def test_job_local_execute(tmp_path):
    script = tmp_path / "train.py"
    script.write_text("print('hello from job'); open('out.txt','w').write('done')\n")
    job = Job(
        "j1", address=None, script_path=str(script),
        remote_dir=str(tmp_path / "jobs"), fetch=("out.txt",),
    )
    code = job.run(local_artifact_dir=str(tmp_path / "artifacts"))
    assert code == 0
    assert "hello from job" in job.output
    assert (tmp_path / "artifacts" / "out.txt").read_text() == "done"


def test_job_failure_code(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("raise SystemExit(3)\n")
    job = Job("j2", address=None, script_path=str(script),
              remote_dir=str(tmp_path / "jobs"))
    assert job.run() == 3


def test_punchcard(tmp_path):
    s1 = tmp_path / "a.py"; s1.write_text("print('a')\n")
    s2 = tmp_path / "b.py"; s2.write_text("print('b')\n")
    spec = {
        "jobs": [
            {"job_name": "a", "address": None, "script_path": str(s1),
             "remote_dir": str(tmp_path / "jobs")},
            {"job_name": "b", "address": None, "script_path": str(s2),
             "remote_dir": str(tmp_path / "jobs")},
        ]
    }
    p = tmp_path / "card.json"
    p.write_text(json.dumps(spec))
    codes = Punchcard(str(p)).run()
    assert codes == [0, 0]


def test_punchcard_stops_on_failure(tmp_path):
    bad = tmp_path / "bad.py"; bad.write_text("raise SystemExit(1)\n")
    ok = tmp_path / "ok.py"; ok.write_text("print('ok')\n")
    spec = {"jobs": [
        {"job_name": "bad", "address": None, "script_path": str(bad),
         "remote_dir": str(tmp_path / "jobs")},
        {"job_name": "ok", "address": None, "script_path": str(ok),
         "remote_dir": str(tmp_path / "jobs")},
    ]}
    p = tmp_path / "card.json"; p.write_text(json.dumps(spec))
    assert Punchcard(str(p)).run() == [1]
