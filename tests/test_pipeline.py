"""Pipeline-parallel tests: pipelined == sequential, and it differentiates."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.parallel.mesh import make_mesh
from distkeras_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_shardings,
    stack_stage_params,
)


def _stage_fn(params, x):
    # a residual MLP block: x + tanh(x @ W + b)
    return x + jnp.tanh(x @ params["w"] + params["b"])


def _stages(rng, P, D):
    return [
        {
            "w": rng.normal(size=(D, D)).astype(np.float32) * 0.3,
            "b": rng.normal(size=(D,)).astype(np.float32) * 0.1,
        }
        for _ in range(P)
    ]


def _sequential(stages, x):
    for s in stages:
        x = _stage_fn(s, x)
    return x


@pytest.mark.parametrize("P,M", [(4, 4), (4, 8), (2, 3), (8, 2)])
def test_pipeline_matches_sequential(rng, P, M):
    D, B = 16, 4
    mesh = make_mesh({"pp": P})
    stages = _stages(rng, P, D)
    stacked = stack_stage_params(stages)
    x = rng.normal(size=(M, B, D)).astype(np.float32)
    out = pipeline_apply(_stage_fn, stacked, x, mesh)
    ref = np.stack([_sequential(stages, x[m]) for m in range(M)])
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_sequential(rng):
    P, M, D, B = 4, 4, 8, 2
    mesh = make_mesh({"pp": P})
    stages = _stages(rng, P, D)
    stacked = stack_stage_params(stages)
    x = rng.normal(size=(M, B, D)).astype(np.float32)
    target = rng.normal(size=(M, B, D)).astype(np.float32)

    def loss_pipe(sp):
        return jnp.mean((pipeline_apply(_stage_fn, sp, x, mesh) - target) ** 2)

    def loss_seq(sp):
        stages_ = [jax.tree.map(lambda a: a[i], sp) for i in range(P)]
        out = jnp.stack([_sequential(stages_, x[m]) for m in range(M)])
        return jnp.mean((out - target) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)


def test_pipeline_sharded_params_layout(rng):
    P, D = 8, 8
    mesh = make_mesh({"pp": P})
    stacked = stack_stage_params(_stages(rng, P, D))
    p_sh, io_sh = pipeline_shardings(mesh)
    placed = jax.device_put(stacked, p_sh)
    # each device holds exactly one stage's weights
    assert {s.data.shape for s in placed["w"].addressable_shards} == {(1, D, D)}

@pytest.mark.parametrize("P,V,M", [(4, 2, 4), (2, 4, 6), (4, 2, 5), (2, 2, 2)])
def test_interleaved_pipeline_matches_sequential(rng, P, V, M):
    """virtual_stages=V: the round-robin stack + group-staggered injection
    must reproduce plain sequential application (incl. partial last group)."""
    D, B = 16, 8
    mesh = make_mesh({"dp": 8 // P, "pp": P} if P < 8 else {"pp": P})
    stages = _stages(rng, P * V, D)
    stacked = stack_stage_params(stages, virtual_stages=V)
    x = rng.normal(size=(M, B, D)).astype(np.float32)
    out = pipeline_apply(_stage_fn, stacked, x, mesh, virtual_stages=V)
    ref = np.stack([_sequential(stages, x[m]) for m in range(M)])
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_interleaved_pipeline_gradients(rng):
    P, V, M, D, B = 4, 2, 4, 8, 2
    mesh = make_mesh({"pp": P})
    stages = _stages(rng, P * V, D)
    stacked = stack_stage_params(stages, virtual_stages=V)
    x = rng.normal(size=(M, B, D)).astype(np.float32)

    def loss_pipe(sp):
        return jnp.mean(
            pipeline_apply(_stage_fn, sp, x, mesh, virtual_stages=V) ** 2
        )

    def loss_seq(ws):
        out = jnp.stack([_sequential(ws, x[m]) for m in range(M)])
        return jnp.mean(out ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stages)
    # stacked position d*V + v holds logical stage v*P + d
    for d in range(P):
        for v in range(V):
            for key in ("w", "b"):
                np.testing.assert_allclose(
                    np.asarray(g_pipe[key][d * V + v]),
                    np.asarray(g_seq[v * P + d][key]),
                    atol=1e-5, rtol=1e-4,
                )


def test_stack_stage_params_rejects_indivisible(rng):
    with pytest.raises(ValueError, match="virtual_stages"):
        stack_stage_params(_stages(rng, 6, 4), virtual_stages=4)

@pytest.mark.slow
def test_pipeline_schedule_property(rng):
    """Schedule invariant over (P, V, M): the interleaved rotation equals
    sequential application for every divisor mesh and ragged microbatch
    count (keep the sweep small — each case is a fresh XLA compile)."""
    D, B = 8, 8
    for P, V, M in [(2, 1, 3), (2, 3, 4), (8, 2, 3), (4, 4, 9), (2, 2, 7)]:
        mesh = make_mesh({"dp": 8 // P, "pp": P} if P < 8 else {"pp": P})
        stages = _stages(rng, P * V, D)
        x = rng.normal(size=(M, B, D)).astype(np.float32)
        out = pipeline_apply(
            _stage_fn, stack_stage_params(stages, virtual_stages=V), x, mesh,
            virtual_stages=V,
        )
        ref = np.stack([_sequential(stages, x[m]) for m in range(M)])
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5,
                                   rtol=1e-5, err_msg=f"P={P} V={V} M={M}")


@pytest.mark.slow
def test_pipeline_memory_bench_remat_reduces_peak():
    """Guard the activation-memory accounting (docs/parallel.md table):
    the bench runs, reports XLA-measured temp per schedule, and remat
    strictly reduces the peak for both V."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(
        BENCH_MODE="memory", BENCH_PP="2", BENCH_MICRO="4",
        BENCH_DIM="64", BENCH_SEQ="32", BENCH_MB="2",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
    )
    out = subprocess.run(
        [sys.executable, "benchmarks/pipeline_bench.py"],
        capture_output=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr.decode()[-2000:]
    rec = json.loads(out.stdout.decode().strip().splitlines()[-1])
    assert rec["metric"] == "pipeline_activation_memory"
    for v in ("v1", "v2"):
        plain = rec[f"{v}_plain"]["measured_temp_mb"]
        remat = rec[f"{v}_remat"]["measured_temp_mb"]
        assert remat < plain, rec
    # the hand-rolled 1F1B engine must beat even the remat schedule
    assert (
        rec["true_1f1b"]["measured_temp_mb"]
        < rec["v1_remat"]["measured_temp_mb"]
    ), rec
