"""Protocol math unit tests (pure update rules, no threads)."""

import numpy as np

from distkeras_tpu.parallel.protocols import (
    ADAGProtocol,
    AEASGDProtocol,
    DOWNPOURProtocol,
    DynSGDProtocol,
    EAMSGDProtocol,
)


def _center():
    return {"w": np.zeros(4, np.float32)}


def _delta(v):
    return {"w": np.full(4, v, np.float32)}


def test_downpour_commit_adds_delta():
    p = DOWNPOURProtocol()
    center, n = p.server_commit(_center(), 0, {"delta": _delta(1.0)}, num_workers=4)
    assert np.allclose(center["w"], 1.0)
    assert n == 1


def test_adag_commit_normalizes_by_num_workers():
    p = ADAGProtocol()
    center, n = p.server_commit(_center(), 0, {"delta": _delta(8.0)}, num_workers=4)
    assert np.allclose(center["w"], 2.0)  # 8 / 4
    assert n == 1


def test_dynsgd_staleness_damping():
    p = DynSGDProtocol()
    # worker pulled at num_updates=2; server is now at 5 -> staleness 3
    center, n = p.server_commit(
        _center(), 5, {"delta": _delta(4.0), "last_update": 2}, num_workers=2
    )
    assert np.allclose(center["w"], 1.0)  # 4 / (3 + 1)
    assert n == 6


def test_dynsgd_zero_staleness_full_delta():
    p = DynSGDProtocol()
    center, n = p.server_commit(
        _center(), 3, {"delta": _delta(4.0), "last_update": 3}, num_workers=2
    )
    assert np.allclose(center["w"], 4.0)


def test_aeasgd_elastic_symmetry():
    """Worker moves toward center by e; server center moves toward worker by e."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)

    class FakeClient:
        def __init__(self):
            self.committed = None
            self.center = {"w": np.zeros(4, np.float32)}

        def pull(self):
            return self.center, 0

        def commit(self, payload):
            self.committed = payload

    client = FakeClient()
    local = {"w": np.full(4, 2.0, np.float32)}
    new_local, carry = p.worker_window(local, None, client)
    # e = rho*lr*(local - center) = 0.5 * 2 = 1
    assert np.allclose(np.asarray(new_local["w"]), 1.0)
    assert np.allclose(np.asarray(client.committed["delta"]["w"]), 1.0)
    # server applies center += e
    center, _ = p.server_commit(client.center, 0, client.committed, 2)
    assert np.allclose(center["w"], 1.0)


def test_eamsgd_local_optimizer_adds_momentum():
    import optax

    p = EAMSGDProtocol(momentum=0.9)
    opt = p.local_optimizer(optax.sgd(0.1))
    params = {"w": np.ones(2, np.float32)}
    state = opt.init(params)
    g = {"w": np.ones(2, np.float32)}
    u1, state = opt.update(g, state, params)
    u2, state = opt.update(g, state, params)
    # with nesterov trace, second update is larger in magnitude than first
    assert abs(u2["w"][0]) > abs(u1["w"][0])


def test_dynsgd_damps_stale_worker_end_to_end():
    """Behavioral: a worker committing with an old last_update moves the
    center less than a fresh worker committing the same delta."""
    from distkeras_tpu.parallel.ps import ParameterServerService

    ps = ParameterServerService(DynSGDProtocol(), {"w": np.zeros(1)}, 2)
    ps.start()
    try:
        fresh, stale = ps.client(), ps.client()
        # advance the server 5 updates with fresh pulls each time
        for _ in range(5):
            _, last = fresh.pull()
            fresh.commit({"delta": {"w": np.ones(1)}, "last_update": last})
        _, n = fresh.pull()
        assert n == 5
        before = ps.get_model()["w"][0]
        # stale worker pulled long ago (last_update=0): staleness 5 -> /6
        stale.commit({"delta": {"w": np.full(1, 6.0)}, "last_update": 0})
        stale.pull()
        after = ps.get_model()["w"][0]
        assert np.isclose(after - before, 1.0)  # 6 / (5+1)
    finally:
        ps.stop()


class _FusedFakePS:
    """In-process stand-in for the PS loop: routes commit_pull through the
    protocol's real server hooks against a center it owns."""

    def __init__(self, protocol, center, num_workers=2):
        self.protocol = protocol
        self.center = center
        self.num_updates = 0
        self.num_workers = num_workers

    def pull(self):
        return self.center, self.num_updates

    def commit_pull(self, payload):
        self.center, self.num_updates, reply = self.protocol.server_commit_pull(
            self.center, self.num_updates, payload, self.num_workers
        )
        return reply


def _perturb(tree, seed, scale=1e-3):
    """Simulate a window of local training: small parameter drift."""
    rng = np.random.default_rng(seed)
    return {k: v + scale * rng.normal(size=v.shape).astype(v.dtype)
            for k, v in tree.items()}


def test_aeasgd_fused_mirror_stays_bit_identical():
    """Steady-state elastic exchange: worker and PS advance the shared
    mirror from the same wire bytes, so the two copies never diverge."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    ps = _FusedFakePS(p, {"w": np.zeros(64, np.float32)})
    params, carry = p.worker_begin(ps, None)
    for seed in range(4):
        params = _perturb(params, seed)
        params, carry = p.worker_window(params, carry, ps)
        assert carry.worker_id in p._mirrors
        server_mirror = p._mirrors[carry.worker_id]
        for k in server_mirror:
            assert np.array_equal(
                np.asarray(server_mirror[k]), np.asarray(carry.mirror[k])
            ), "worker/PS mirror copies diverged"


def test_aeasgd_fused_mirror_force_matches_exact():
    """The bf16 mirror encoding perturbs the elastic force only at bf16
    rounding scale: compare against an exact full-precision replica."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    center0 = {"w": np.linspace(-1, 1, 128).astype(np.float32)}
    ps = _FusedFakePS(p, {k: v.copy() for k, v in center0.items()})
    params, carry = p.worker_begin(ps, None)

    exact_center = {k: v.copy() for k, v in center0.items()}
    exact_params = {k: np.asarray(v).copy() for k, v in params.items()}
    alpha = p.rho * p.learning_rate
    for seed in range(3):
        params = _perturb(params, seed)
        exact_params = _perturb(exact_params, seed)
        params, carry = p.worker_window(params, carry, ps)
        e = {k: alpha * (exact_params[k] - exact_center[k]) for k in exact_params}
        exact_params = {k: exact_params[k] - e[k] for k in exact_params}
        exact_center = {k: exact_center[k] + e[k] for k in exact_center}
    got = np.asarray(params["w"])
    want = exact_params["w"]
    # bf16 has 8 mantissa bits (~2^-9 relative); a handful of windows keeps
    # the accumulated wire-rounding well under 1e-2 absolute on O(1) weights.
    assert np.max(np.abs(got - want)) < 1e-2
    assert np.max(np.abs(np.asarray(ps.center["w"]) - exact_center["w"])) < 1e-2


def test_aeasgd_rebootstrap_after_mirror_loss():
    """A PS that lost its per-worker mirror (restart) answers with the
    re-bootstrap flag: the worker skips the window, then re-sends full
    params and the exchange resumes."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    ps = _FusedFakePS(p, {"w": np.zeros(8, np.float32)})
    params, carry = p.worker_begin(ps, None)
    params, carry = p.worker_window(_perturb(params, 0), carry, ps)
    assert carry.mirror is not None

    p._mirrors.clear()  # simulate PS restart from checkpoint
    before = {k: np.asarray(v).copy() for k, v in params.items()}
    n_before = ps.num_updates
    params, carry = p.worker_window(params, carry, ps)
    assert carry.mirror is None  # told to re-bootstrap
    assert np.array_equal(np.asarray(params["w"]), before["w"])  # no-op window
    assert ps.num_updates == n_before  # nothing applied server-side

    params, carry = p.worker_window(_perturb(params, 1), carry, ps)
    assert carry.mirror is not None and carry.worker_id in p._mirrors


def test_aeasgd_duplicate_reply_is_replayed_verbatim():
    """A deduped fused retry gets the recorded reply, not a recomputed force
    (the mirror already advanced when the original commit applied)."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    center = {"w": np.zeros(16, np.float32)}
    local = {"w": np.full(16, 2.0, np.float32)}
    payload = {"local": local, "worker_id": "w0", "last_update": 0}
    center, n, reply = p.server_commit_pull(center, 0, payload, 2)
    replay, counter = p.server_duplicate_reply(center, n, payload)
    assert counter == reply[1]
    assert np.array_equal(np.asarray(replay["w"]), np.asarray(reply[0]["w"]))


def test_aeasgd_rebootstrap_duplicate_replays_flag():
    """A deduped retry of a rebootstrap-flagged exchange must replay the
    flagged counter — never the raw center (which the worker would subtract
    as if it were the elastic force)."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    center = {"w": np.full(8, 7.0, np.float32)}
    diff = {"w": np.zeros(8, np.float32)}
    payload = {"elastic_diff": diff, "worker_id": "w-lost", "last_update": 0}
    # Original exchange against a PS with no mirror for this worker.
    center2, n2, (tree, counter) = p.server_commit_pull(center, 5, payload, 2)
    assert counter & (1 << 63)
    # Retry after the reply was lost: same flagged answer, zero tree.
    replay, dup_counter = p.server_duplicate_reply(center2, n2, payload)
    assert dup_counter & (1 << 63)
    assert np.allclose(np.asarray(replay["w"]), 0.0)
    # Even with _last_reply wiped (PS restart between original and retry),
    # the fallback still flags rather than returning the center.
    p._last_reply.clear()
    replay2, dup2 = p.server_duplicate_reply(center2, n2, payload)
    assert dup2 & (1 << 63)
    assert np.allclose(np.asarray(replay2["w"]), 0.0)


def test_aeasgd_mirror_state_is_bounded_under_worker_churn():
    """Worker ids are per-incarnation; restarts must not leak model-sized
    mirrors on the PS (LRU eviction beyond 2×num_workers)."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    center = {"w": np.zeros(16, np.float32)}
    num_workers = 3
    for i in range(20):  # 20 worker incarnations
        local = {"w": np.full(16, float(i), np.float32)}
        center, _, _ = p.server_commit_pull(
            center, i, {"local": local, "worker_id": f"w{i}", "last_update": 0},
            num_workers,
        )
    assert len(p._mirrors) <= 2 * num_workers
    # Replies age on their own clock, twice the mirror bound (they must
    # outlive a mirror eviction to keep dedupe replay exact — ADVICE r4).
    assert len(p._last_reply) <= 4 * num_workers
    # An evicted worker's next diff gets the re-bootstrap flag, not garbage.
    _, _, (_, counter) = p.server_commit_pull(
        center, 20,
        {"elastic_diff": {"w": np.zeros(16, np.float32)},
         "worker_id": "w0", "last_update": 0},
        num_workers,
    )
    assert counter & (1 << 63)


def test_aeasgd_lost_mirror_churn_does_not_grow_reply_state():
    """Incarnations that never bootstrap (elastic_diff against a lost
    mirror, then die) must leave no model-sized state behind."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    center = {"w": np.zeros(16, np.float32)}
    for i in range(50):
        _, _, (_, counter) = p.server_commit_pull(
            center, i,
            {"elastic_diff": {"w": np.zeros(16, np.float32)},
             "worker_id": f"ghost{i}", "last_update": 0},
            2,
        )
        assert counter & (1 << 63)
    assert len(p._last_reply) == 0
    assert len(p._mirrors) == 0


def test_aeasgd_reply_outlives_mirror_eviction():
    """ADVICE r4: a lost-reply retry arriving AFTER the worker's mirror was
    LRU-evicted must still replay the recorded answer (the commit DID move
    the center) instead of flagging a re-bootstrap — otherwise the worker
    skips its side of an elastic pull the center already took."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    center = {"w": np.zeros(16, np.float32)}
    num_workers = 2  # mirror bound 4, reply bound 8
    local = {"w": np.full(16, 2.0, np.float32)}
    payload = {"local": local, "worker_id": "w0", "last_update": 0}
    center, n, reply = p.server_commit_pull(center, 0, payload, num_workers)
    for i in range(5):  # churn past the mirror bound, not the reply bound
        center, n, _ = p.server_commit_pull(
            center, n,
            {"local": {"w": np.full(16, float(i), np.float32)},
             "worker_id": f"other{i}", "last_update": 0},
            num_workers,
        )
    assert "w0" not in p._mirrors  # mirror gone...
    replay, counter = p.server_duplicate_reply(center, n, payload)
    assert not (counter & (1 << 63))  # ...but the retry is NOT re-bootstrapped
    assert counter == reply[1]
    np.testing.assert_array_equal(
        np.asarray(replay["w"], np.float32), np.asarray(reply[0]["w"], np.float32)
    )


def test_aeasgd_host_state_within_budget():
    """PS-side mirror+reply bytes for a known model stay within the
    documented host_state_budget (bf16 mirrors are half of f32)."""
    n_params, num_workers = 1024, 3
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    center = {"w": np.zeros(n_params, np.float32)}
    rng = np.random.default_rng(0)
    for i in range(12):
        local = {"w": rng.normal(size=n_params).astype(np.float32)}
        center, _, _ = p.server_commit_pull(
            center, i,
            {"local": local, "worker_id": f"w{i % num_workers}",
             "last_update": 0},
            num_workers,
        )
    mirror_bytes = sum(np.asarray(m["w"]).nbytes for m in p._mirrors.values())
    reply_bytes = sum(np.asarray(r[0]["w"]).nbytes for r in p._last_reply.values())
    assert mirror_bytes == len(p._mirrors) * 2 * n_params  # stored bf16
    assert mirror_bytes + reply_bytes <= p.host_state_budget(n_params, num_workers)
    # f32 opt-out restores the old storage
    p32 = AEASGDProtocol(rho=5.0, learning_rate=0.1, mirror_dtype="float32")
    center = {"w": np.zeros(n_params, np.float32)}
    center, _, _ = p32.server_commit_pull(
        center, 0,
        {"local": {"w": np.ones(n_params, np.float32)}, "worker_id": "a",
         "last_update": 0},
        1,
    )
    assert np.asarray(p32._mirrors["a"]["w"]).dtype == np.float32


def test_aeasgd_local_transport_skips_mirror_machinery():
    """In-process transport (wire_is_local): the elastic exchange ships the
    full-precision local tree with NO worker_id, so the PS keeps no mirror
    or reply state and the worker keeps no mirror — the wire-compression
    state machine only runs where there is a wire (round-5 fix for the
    1.52x loopback overhead; BASELINE.md round-5 table)."""
    from distkeras_tpu.parallel.ps import ParameterServerService

    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)
    svc = ParameterServerService(p, {"w": np.zeros(16, np.float32)}, 1)
    svc.start()
    try:
        client = svc.client()
        assert getattr(client, "wire_is_local", False)
        params, carry = p.worker_begin(client, None)
        for i in range(3):
            params, carry = p.worker_window(_perturb(params, i), carry, client)
        assert carry.mirror is None          # worker side: no mirror kept
        assert not carry.worker_id
        assert len(p._mirrors) == 0          # PS side: no bookkeeping
        assert len(p._last_reply) == 0
        assert svc.num_commits == 3          # the exchanges DID apply
    finally:
        svc.stop()
