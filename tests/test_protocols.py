"""Protocol math unit tests (pure update rules, no threads)."""

import numpy as np

from distkeras_tpu.parallel.protocols import (
    ADAGProtocol,
    AEASGDProtocol,
    DOWNPOURProtocol,
    DynSGDProtocol,
    EAMSGDProtocol,
)


def _center():
    return {"w": np.zeros(4, np.float32)}


def _delta(v):
    return {"w": np.full(4, v, np.float32)}


def test_downpour_commit_adds_delta():
    p = DOWNPOURProtocol()
    center, n = p.server_commit(_center(), 0, {"delta": _delta(1.0)}, num_workers=4)
    assert np.allclose(center["w"], 1.0)
    assert n == 1


def test_adag_commit_normalizes_by_num_workers():
    p = ADAGProtocol()
    center, n = p.server_commit(_center(), 0, {"delta": _delta(8.0)}, num_workers=4)
    assert np.allclose(center["w"], 2.0)  # 8 / 4
    assert n == 1


def test_dynsgd_staleness_damping():
    p = DynSGDProtocol()
    # worker pulled at num_updates=2; server is now at 5 -> staleness 3
    center, n = p.server_commit(
        _center(), 5, {"delta": _delta(4.0), "last_update": 2}, num_workers=2
    )
    assert np.allclose(center["w"], 1.0)  # 4 / (3 + 1)
    assert n == 6


def test_dynsgd_zero_staleness_full_delta():
    p = DynSGDProtocol()
    center, n = p.server_commit(
        _center(), 3, {"delta": _delta(4.0), "last_update": 3}, num_workers=2
    )
    assert np.allclose(center["w"], 4.0)


def test_aeasgd_elastic_symmetry():
    """Worker moves toward center by e; server center moves toward worker by e."""
    p = AEASGDProtocol(rho=5.0, learning_rate=0.1)

    class FakeClient:
        def __init__(self):
            self.committed = None
            self.center = {"w": np.zeros(4, np.float32)}

        def pull(self):
            return self.center, 0

        def commit(self, payload):
            self.committed = payload

    client = FakeClient()
    local = {"w": np.full(4, 2.0, np.float32)}
    new_local, carry = p.worker_window(local, None, client)
    # e = rho*lr*(local - center) = 0.5 * 2 = 1
    assert np.allclose(np.asarray(new_local["w"]), 1.0)
    assert np.allclose(np.asarray(client.committed["delta"]["w"]), 1.0)
    # server applies center += e
    center, _ = p.server_commit(client.center, 0, client.committed, 2)
    assert np.allclose(center["w"], 1.0)


def test_eamsgd_local_optimizer_adds_momentum():
    import optax

    p = EAMSGDProtocol(momentum=0.9)
    opt = p.local_optimizer(optax.sgd(0.1))
    params = {"w": np.ones(2, np.float32)}
    state = opt.init(params)
    g = {"w": np.ones(2, np.float32)}
    u1, state = opt.update(g, state, params)
    u2, state = opt.update(g, state, params)
    # with nesterov trace, second update is larger in magnitude than first
    assert abs(u2["w"][0]) > abs(u1["w"][0])


def test_dynsgd_damps_stale_worker_end_to_end():
    """Behavioral: a worker committing with an old last_update moves the
    center less than a fresh worker committing the same delta."""
    from distkeras_tpu.parallel.ps import ParameterServerService

    ps = ParameterServerService(DynSGDProtocol(), {"w": np.zeros(1)}, 2)
    ps.start()
    try:
        fresh, stale = ps.client(), ps.client()
        # advance the server 5 updates with fresh pulls each time
        for _ in range(5):
            _, last = fresh.pull()
            fresh.commit({"delta": {"w": np.ones(1)}, "last_update": last})
        _, n = fresh.pull()
        assert n == 5
        before = ps.get_model()["w"][0]
        # stale worker pulled long ago (last_update=0): staleness 5 -> /6
        stale.commit({"delta": {"w": np.full(1, 6.0)}, "last_update": 0})
        stale.pull()
        after = ps.get_model()["w"][0]
        assert np.isclose(after - before, 1.0)  # 6 / (5+1)
    finally:
        ps.stop()
