import numpy as np
import pytest

from distkeras_tpu.data.dataset import Dataset
from distkeras_tpu.inference.evaluators import (
    AccuracyEvaluator,
    ConfusionMatrixEvaluator,
    PrecisionRecallEvaluator,
)


def _ds():
    return Dataset.from_arrays(
        prediction_index=np.array([1, 0, 1, 1, 0, 0]),
        label=np.array([1, 0, 0, 1, 1, 0]),
    )


def test_accuracy():
    assert AccuracyEvaluator().evaluate(_ds()) == pytest.approx(4 / 6)


def test_accuracy_one_hot_label():
    ds = Dataset.from_arrays(
        prediction_index=np.array([1, 0]),
        label=np.array([[0.0, 1.0], [0.0, 1.0]]),
    )
    assert AccuracyEvaluator().evaluate(ds) == pytest.approx(0.5)


def test_accuracy_length_mismatch():
    ds = Dataset.from_arrays(a=np.zeros(3), b=np.zeros(3))
    ds2 = ds.with_column("prediction_index", np.zeros(3))
    with pytest.raises(KeyError):
        AccuracyEvaluator(label_col="missing").evaluate(ds2)


def test_precision_recall_f1():
    out = PrecisionRecallEvaluator().evaluate(_ds())
    # preds==1: idx 0,2,3 -> tp=2 (0,3), fp=1 (2); fn=1 (idx 4)
    assert out["tp"] == 2 and out["fp"] == 1 and out["fn"] == 1
    assert out["precision"] == pytest.approx(2 / 3)
    assert out["recall"] == pytest.approx(2 / 3)
    assert out["f1"] == pytest.approx(2 / 3)


def test_confusion_matrix():
    m = ConfusionMatrixEvaluator(2).evaluate(_ds())
    # rows=true, cols=pred
    assert m[1, 1] == 2 and m[0, 0] == 2 and m[0, 1] == 1 and m[1, 0] == 1
    assert m.sum() == 6
