"""Flash-attention Pallas kernel vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(rng, B=2, S=128, H=2, D=16, dtype=np.float32):
    mk = lambda: np.asarray(rng.normal(size=(B, S, H, D)), dtype)
    return mk(), mk(), mk()


def test_flash_matches_dense(rng):
    q, k, v = _qkv(rng)
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_flash_causal_matches_dense(rng):
    q, k, v = _qkv(rng, S=64)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_flash_single_block(rng):
    q, k, v = _qkv(rng, S=32)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_flash_gradients_match_dense(rng):
    q, k, v = _qkv(rng, B=1, S=32, H=1, D=8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=16, block_k=16) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3, rtol=5e-3)


def test_flash_rejects_ragged_seq(rng):
    q, k, v = _qkv(rng, S=100)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_flash_causal_gradients_match_dense(rng):
    q, k, v = _qkv(rng, B=1, S=32, H=2, D=8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=16, block_k=16) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3, rtol=5e-3)


def test_flash_gradients_multihead_rect_blocks(rng):
    q, k, v = _qkv(rng, B=2, S=64, H=2, D=16)

    def loss_flash(q, k, v):
        return jnp.mean(flash_attention(q, k, v, block_q=32, block_k=16) ** 2)

    def loss_dense(q, k, v):
        return jnp.mean(dot_product_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3)
