"""Host-gap accounting on a fake clock: HostGapTracker's arithmetic is
deterministic given scripted dispatch/harvest instants, so every number
the serving_host_gap_seconds family reports is asserted exactly here —
including the one subtlety that makes the metric honest at depth 1: a
dispatch issued while a tick is still in flight records a 0 gap (the
device queue was never observed empty), never a bogus positive one.
"""

import pytest

np = pytest.importorskip("numpy")  # noqa: F401  (parity with suite style)

from distkeras_tpu.serving.metrics import (  # noqa: E402
    HostGapTracker,
    ServingMetrics,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_depth0_sequence_measures_full_host_gap():
    """Serialized dispatch→harvest→(host work)→dispatch: the gap is
    exactly the host window between harvest end and the next dispatch."""
    clk = FakeClock()
    hg = HostGapTracker(clock=clk)
    # tick 0: dispatch at t=0, harvest 0.00..0.10 (device time 100ms)
    clk.t = 0.0
    hg.tick_dispatched()
    clk.t = 0.001
    hg.harvest_started()
    clk.t = 0.101
    hg.harvest_ended()
    assert hg.last_harvest_wait == pytest.approx(0.1)
    # host does 40ms of bookkeeping, then dispatches tick 1
    clk.t = 0.141
    hg.tick_dispatched()
    assert hg.last_gap == pytest.approx(0.04)
    clk.t = 0.142
    hg.harvest_started()
    clk.t = 0.241
    hg.harvest_ended()
    # gaps: [0.0 (first tick), 0.04]; intervals: [0.141]
    assert list(hg.gaps) == [0.0, pytest.approx(0.04)]
    assert hg.idle_ratio == pytest.approx(0.04 / 0.141)


def test_depth1_pipelined_dispatch_records_zero_gap():
    """Dispatch-before-harvest: at dispatch time a tick is still
    pending, so the device queue was never empty — gap must be 0 no
    matter what the clock says."""
    clk = FakeClock()
    hg = HostGapTracker(clock=clk)
    clk.t = 0.0
    hg.tick_dispatched()        # tick 0
    clk.t = 0.05
    hg.tick_dispatched()        # tick 1, tick 0 still in flight
    assert hg.last_gap == 0.0
    clk.t = 0.06
    hg.harvest_started()
    clk.t = 0.10
    hg.harvest_ended()          # tick 0 harvested
    clk.t = 0.11
    hg.tick_dispatched()        # tick 2 — but tick 1 still pending
    assert hg.last_gap == 0.0   # queue still never observed empty
    clk.t = 0.12
    hg.harvest_started()
    clk.t = 0.13
    hg.harvest_ended()          # tick 1
    clk.t = 0.14
    hg.harvest_started()
    clk.t = 0.20
    hg.harvest_ended()          # tick 2; pipe empty now
    clk.t = 0.23
    hg.tick_dispatched()        # tick 3, after a real 30ms idle window
    assert hg.last_gap == pytest.approx(0.03)
    assert list(hg.gaps) == [0.0, 0.0, 0.0, pytest.approx(0.03)]


def test_idle_ratio_window_alignment_and_clamp():
    """idle_ratio divides the matched window (gaps beyond the first
    dispatch) by the dispatch intervals and clamps at 1.0."""
    clk = FakeClock()
    hg = HostGapTracker(clock=clk)
    assert hg.idle_ratio is None  # no intervals yet
    for t_d, t_h in ((0.0, 0.1), (1.0, 1.1), (2.0, 2.1)):
        clk.t = t_d
        hg.tick_dispatched()
        clk.t = t_h - 0.09
        hg.harvest_started()
        clk.t = t_h
        hg.harvest_ended()
    # gaps: [0, 0.9, 0.9]; intervals: [1.0, 1.0] -> matched gaps [.9,.9]
    assert hg.idle_ratio == pytest.approx(0.9)
    s = hg.summary()
    assert s["device_idle_ratio"] == pytest.approx(0.9)
    assert s["host_gap_p99_s"] == pytest.approx(0.9)


def test_tracker_publishes_histogram_and_gauge():
    """The registry mirror: gap observations land in
    serving_host_gap_seconds, the windowed ratio in
    serving_device_idle_ratio."""
    clk = FakeClock()
    m = ServingMetrics()
    m.host_gap = HostGapTracker(
        histogram=m.registry.histogram("serving_host_gap_seconds"),
        idle_gauge=m.registry.gauge("serving_device_idle_ratio"),
        clock=clk)
    hg = m.host_gap
    clk.t = 0.0
    hg.tick_dispatched()
    clk.t = 0.01
    hg.harvest_started()
    clk.t = 0.02
    hg.harvest_ended()
    clk.t = 0.07
    hg.tick_dispatched()
    clk.t = 0.08
    hg.harvest_started()
    clk.t = 0.09
    hg.harvest_ended()
    snap = m.registry.snapshot()
    hist = snap["serving_host_gap_seconds"]
    assert hist["count"] == 2
    gauge = snap["serving_device_idle_ratio"]
    assert gauge["value"] == pytest.approx(0.05 / 0.07)
    s = m.summary()
    assert s["host_gap_p50_s"] >= 0.0
    assert s["device_idle_ratio"] == pytest.approx(0.05 / 0.07)


def test_summary_absent_before_any_tick():
    hg = HostGapTracker(clock=FakeClock())
    assert hg.summary() == {}
    assert hg.gap_p50 is None
