"""True 1F1B engine: gradient parity with sequential autodiff, schedule
properties, and bounded in-flight memory (the ring holds <= P inputs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.parallel.mesh import make_mesh
from distkeras_tpu.parallel.pipeline import stack_stage_params
from distkeras_tpu.parallel.pipeline_1f1b import (
    pipeline_1f1b_value_and_grad,
    ticks_1f1b,
)

P_DEV, D = 4, 8


def _setup(M=6, B=2, seed=0):
    rng = np.random.default_rng(seed)
    stages = [
        {"w": np.asarray(rng.normal(size=(D, D)) * 0.3, np.float32)}
        for _ in range(P_DEV)
    ]
    head = {"h": np.asarray(rng.normal(size=(D, 1)) * 0.3, np.float32)}
    mb = np.asarray(rng.normal(size=(M, B, D)), np.float32)
    labels = np.asarray(rng.normal(size=(M, B, 1)), np.float32)
    return stages, head, mb, labels


def _stage_fn(p, x):
    return x + jnp.tanh(x @ p["w"])


def _last_fn(p, hp, x, y):
    out = _stage_fn(p, x) @ hp["h"]
    return jnp.sum((out - y) ** 2)


def _sequential_loss(stages_list, head, mb, labels):
    total = jnp.float32(0.0)
    for m in range(mb.shape[0]):
        x = mb[m]
        for p in stages_list[:-1]:
            x = _stage_fn(p, x)
        total = total + _last_fn(stages_list[-1], head, x, labels[m])
    return total


def test_1f1b_matches_sequential_autodiff():
    stages, head, mb, labels = _setup()
    mesh = make_mesh({"pp": P_DEV})
    stacked = stack_stage_params(stages)
    loss, sg, hg, cot = jax.jit(
        lambda s, h, x, y: pipeline_1f1b_value_and_grad(
            _stage_fn, _last_fn, s, h, x, y, mesh
        )
    )(stacked, head, mb, labels)

    ref_loss, (ref_sg_list, ref_hg, ref_cot) = jax.value_and_grad(
        lambda s, h, x: _sequential_loss(s, h, x, labels), argnums=(0, 1, 2)
    )(stages, head, jnp.asarray(mb))

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for i in range(P_DEV):
        np.testing.assert_allclose(
            np.asarray(sg["w"][i]), np.asarray(ref_sg_list[i]["w"]),
            atol=1e-4, rtol=1e-4,
        )
    np.testing.assert_allclose(
        np.asarray(hg["h"]), np.asarray(ref_hg["h"]), atol=1e-4, rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(cot), np.asarray(ref_cot), atol=1e-4, rtol=1e-4
    )


def test_1f1b_m_larger_than_p():
    """M > P exercises ring-buffer reuse (slot m % P overwritten only
    after its backward consumed it — the schedule guarantees it)."""
    stages, head, mb, labels = _setup(M=11)
    mesh = make_mesh({"pp": P_DEV})
    stacked = stack_stage_params(stages)
    loss, sg, hg, cot = jax.jit(
        lambda s, h, x, y: pipeline_1f1b_value_and_grad(
            _stage_fn, _last_fn, s, h, x, y, mesh
        )
    )(stacked, head, mb, labels)
    ref_loss, ref_sg_list = jax.value_and_grad(
        lambda s: _sequential_loss(s, head, jnp.asarray(mb), labels)
    )(stages)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for i in range(P_DEV):
        np.testing.assert_allclose(
            np.asarray(sg["w"][i]), np.asarray(ref_sg_list[i]["w"]),
            atol=1e-4, rtol=1e-4,
        )


def test_1f1b_tick_count():
    assert ticks_1f1b(8, 4) == 2 * 8 + 2 * 4 - 2
    assert ticks_1f1b(1, 1) == 2  # one F tick, one B tick


def test_1f1b_rejects_wrong_stage_count():
    stages, head, mb, labels = _setup()
    mesh = make_mesh({"pp": P_DEV})
    stacked = stack_stage_params(stages[:2])
    with pytest.raises(ValueError, match="stages"):
        pipeline_1f1b_value_and_grad(
            _stage_fn, _last_fn, stacked, head, mb, labels, mesh
        )


@pytest.mark.slow
def test_pipeline_trainer_1f1b_matches_gpipe():
    """schedule='1f1b' trains the same math as the scanned gpipe schedule:
    identical model/data/seed produce matching loss trajectories (both are
    exact batch-mean losses; no stochastic layers)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import BertConfig, _make

    VOCAB, SEQ = 32, 8
    rng = np.random.default_rng(3)
    x = rng.integers(0, VOCAB, size=(64, SEQ)).astype(np.int32)
    ds = dk.Dataset.from_arrays(features=x, label=x.copy())

    def make_trainer(schedule):
        cfg = BertConfig(vocab_size=VOCAB, hidden_size=16, num_layers=4,
                         num_heads=2, mlp_dim=32, max_seq_len=SEQ,
                         dropout_rate=0.0)
        mesh = make_mesh({"pp": P_DEV}, devices=jax.devices()[:P_DEV])
        return dk.PipelineTrainer(
            _make(cfg, SEQ, f"bert_1f1b_{schedule}"),
            worker_optimizer="adam", learning_rate=3e-3,
            num_stages=P_DEV, num_microbatches=4, batch_size=16,
            num_epoch=2, seed=0, schedule=schedule, mesh=mesh,
        )

    t_1f1b = make_trainer("1f1b")
    t_1f1b.train(ds, shuffle=True)
    h1 = t_1f1b.get_history()
    t_gpipe = make_trainer("gpipe")
    t_gpipe.train(ds, shuffle=True)
    h2 = t_gpipe.get_history()
    assert len(h1) == len(h2)
    assert h1[-1]["loss"] < h1[0]["loss"]
    # Trajectory (not single-step) comparison: the two schedules reduce in
    # different orders, and Adam compounds the float noise over 2 epochs —
    # measured drift reached 2.2e-3 under single-threaded-Eigen kernels.
    # A real convention bug (e.g. the 1/dp cotangent mis-scale this test
    # once caught) diverges by orders of magnitude within a few steps.
    for a, b in zip(h1, h2):
        assert abs(a["loss"] - b["loss"]) < 5e-3, (a, b)


def test_pipeline_trainer_1f1b_rejects_unsupported():
    """V>1 stays gpipe-only (the hand-rolled schedule is non-interleaved);
    MoE/ep are no longer rejected — see the composition tests below."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import BertConfig, _make

    cfg = BertConfig(vocab_size=32, hidden_size=16, num_layers=4,
                     num_heads=2, mlp_dim=32, max_seq_len=8)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 32, size=(32, 8)).astype(np.int32)
    ds = __import__("distkeras_tpu").Dataset.from_arrays(
        features=x, label=x.copy()
    )
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    t = dk.PipelineTrainer(
        _make(cfg, 8, "bert_1f1b_v2"), num_stages=2, virtual_stages=2,
        num_microbatches=4, batch_size=16, schedule="1f1b", mesh=mesh,
    )
    with pytest.raises(ValueError, match="virtual_stages"):
        t.train(ds)
    with pytest.raises(ValueError, match="schedule"):
        dk.PipelineTrainer(
            _make(cfg, 8, "bert_sched_bad"), schedule="zigzag"
        )


@pytest.mark.slow
def test_pipeline_trainer_1f1b_dp_dropout_accuracy():
    """The lifted v1 limits together: dp x pp mesh (auto-built from 8
    devices), dropout on (deterministic per-(m, stage) keys), accuracy
    recorded through the engine's aux channel."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import BertConfig, _make

    VOCAB, SEQ = 32, 8
    cfg = BertConfig(vocab_size=VOCAB, hidden_size=16, num_layers=4,
                     num_heads=2, mlp_dim=32, max_seq_len=SEQ,
                     dropout_rate=0.1)
    rng = np.random.default_rng(5)
    x = rng.integers(0, VOCAB, size=(96, SEQ)).astype(np.int32)
    ds = dk.Dataset.from_arrays(features=x, label=x.copy())
    t = dk.PipelineTrainer(
        _make(cfg, SEQ, "bert_1f1b_full"), num_stages=P_DEV,
        schedule="1f1b", num_microbatches=4, batch_size=32,
        num_epoch=4, learning_rate=3e-3, worker_optimizer="adam", seed=0,
    )  # mesh=None: 8 devices / pp=4 -> auto dp=2 x pp=4
    t.train(ds, shuffle=True)
    h = t.get_history()
    assert h[-1]["loss"] < h[0]["loss"]
    assert "accuracy" in h[-1] and 0.0 <= h[-1]["accuracy"] <= 1.0
    assert h[-1]["accuracy"] > h[0]["accuracy"]


@pytest.mark.slow
def test_1f1b_dp_parity_with_gpipe():
    """dp x pp 1F1B must produce the same training trajectory as the
    gpipe schedule on the same mesh — this pins the dp gradient-scaling
    convention (a mis-scaled embedding cotangent diverges under Adam
    within a few steps)."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import BertConfig, _make

    VOCAB, SEQ = 32, 8
    rng = np.random.default_rng(9)
    x = rng.integers(0, VOCAB, size=(64, SEQ)).astype(np.int32)
    ds = dk.Dataset.from_arrays(features=x, label=x.copy())

    def run(schedule):
        cfg = BertConfig(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                         num_heads=2, mlp_dim=32, max_seq_len=SEQ,
                         dropout_rate=0.0)
        mesh = make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
        t = dk.PipelineTrainer(
            _make(cfg, SEQ, f"bert_dp_{schedule}"),
            worker_optimizer="adam", learning_rate=3e-3,
            num_stages=2, num_microbatches=2, batch_size=16,
            num_epoch=2, seed=0, schedule=schedule, mesh=mesh,
        )
        t.train(ds, shuffle=True)
        return t.get_history()

    h1, h2 = run("1f1b"), run("gpipe")
    assert len(h1) == len(h2)
    for a, b in zip(h1, h2):
        assert abs(a["loss"] - b["loss"]) < 2e-3, (a, b)


def _legacy_shard_map() -> bool:
    """True on jax versions before shard_map's promotion to jax.shard_map
    (the utils.platform.get_shard_map fallback lane)."""
    try:
        from jax import shard_map  # noqa: F401

        return False
    except ImportError:
        return True


@pytest.mark.skipif(
    _legacy_shard_map(),
    reason="legacy (pre-jax.shard_map) replication checker cannot prove "
           "ep-replication through the 1F1B engine's divergent tick "
           "branches (the ep-psums sit inside lax.cond arms), so it "
           "rejects the out_specs with a false-positive _SpecError; and "
           "on that jax the checker also DRIVES the rep-aware transpose "
           "rewrites, so check_rep=False runs to silently wrong expert "
           "gradients (verified: loss/aux match sequential, grads do "
           "not). No safe spelling exists before the VMA/pcast API — "
           "the modern checker tracks varying axes through cond and "
           "accepts this program as written.")
def test_1f1b_ep_moe_engine_matches_sequential():
    """MoE/ep composition at the engine level (VERDICT r4 task 1): a toy
    manual-EP stage (local expert slab + psum over ep, differentiable aux)
    on a pp x ep mesh matches sequential full-expert autodiff — loss, the
    weighted-aux gradient flow, ep-sharded expert grads, head grads, and
    input cotangents. Pins the safety argument in the module docstring:
    activations stay ep-invariant so only ep-psums appear inside the
    divergent tick branches."""
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as PS

    PP, EP, E, M, B = 2, 2, 4, 5, 2
    SEED_W = 0.01
    rng = np.random.default_rng(0)
    stages = [
        {"w": np.asarray(rng.normal(size=(D, D)) * 0.3, np.float32),
         "experts": np.asarray(rng.normal(size=(E, D)) * 0.3, np.float32)}
        for _ in range(PP)
    ]
    head = {"h": np.asarray(rng.normal(size=(D, 1)) * 0.3, np.float32)}
    mb = np.asarray(rng.normal(size=(M, B, D)), np.float32)
    labels = np.asarray(rng.normal(size=(M, B, 1)), np.float32)

    def _moe_part(p, x, ep_axis):
        contrib = jnp.tanh(x) * p["experts"].sum()
        aux = jnp.sum(p["experts"] ** 2)
        if ep_axis is not None:
            contrib = lax.psum(contrib, ep_axis)
            aux = lax.psum(aux, ep_axis)
        return contrib, aux

    def make_stage(ep_axis):
        def stage(p, x):
            y = x + jnp.tanh(x @ p["w"])
            c, aux = _moe_part(p, x, ep_axis)
            return y + c, aux
        return stage

    def make_last(ep_axis):
        stage = make_stage(ep_axis)

        def last(p, hp, x, yl):
            y, aux = stage(p, x)
            return jnp.sum((y @ hp["h"] - yl) ** 2), aux
        return last

    mesh = make_mesh({"pp": PP, "ep": EP}, devices=jax.devices()[: PP * EP])
    stacked = stack_stage_params(stages)
    param_specs = {"w": PS("pp"), "experts": PS("pp", "ep")}
    stacked = {
        k: jax.device_put(v, NamedSharding(mesh, param_specs[k]))
        for k, v in stacked.items()
    }
    loss, moe_aux, sg, hg, cot = jax.jit(
        lambda s, h, x, y: pipeline_1f1b_value_and_grad(
            make_stage("ep"), make_last("ep"), s, h, x, y, mesh,
            param_specs=param_specs, stage_aux_seed=SEED_W,
        )
    )(stacked, head, mb, labels)

    seq_stage = make_stage(None)

    def total_loss(stages_list, h, x):
        tot, aux_tot = jnp.float32(0.0), jnp.float32(0.0)
        for m in range(M):
            z = x[m]
            for p in stages_list[:-1]:
                z, aux = seq_stage(p, z)
                aux_tot += aux
            y, aux = seq_stage(stages_list[-1], z)
            aux_tot += aux
            tot += jnp.sum((y @ h["h"] - labels[m]) ** 2)
        return tot + SEED_W * aux_tot, (tot, aux_tot)

    (_, (ref_loss, ref_aux)), (ref_sg, ref_hg, ref_cot) = jax.value_and_grad(
        total_loss, argnums=(0, 1, 2), has_aux=True
    )(stages, head, jnp.asarray(mb))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(float(moe_aux), float(ref_aux), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(hg["h"]), np.asarray(ref_hg["h"]), atol=1e-4, rtol=1e-4
    )
    for i in range(PP):
        for leaf in ("w", "experts"):
            np.testing.assert_allclose(
                np.asarray(sg[leaf][i]), np.asarray(ref_sg[i][leaf]),
                atol=1e-4, rtol=1e-4,
            )
    np.testing.assert_allclose(
        np.asarray(cot), np.asarray(ref_cot), atol=1e-4, rtol=1e-4
    )


@pytest.mark.slow
def test_pipeline_trainer_1f1b_moe_ep_matches_gpipe():
    """The round-4 composition hole, closed end to end: schedule='1f1b'
    with an MoE trunk and experts sharded over ep trains the same
    trajectory (loss AND router aux) as the gpipe schedule on the same
    dp x pp x ep mesh."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import BertConfig, _make

    VOCAB, SEQ = 32, 8
    rng = np.random.default_rng(11)
    x = rng.integers(0, VOCAB, size=(64, SEQ)).astype(np.int32)
    ds = dk.Dataset.from_arrays(features=x, label=x.copy())

    def run(schedule):
        cfg = BertConfig(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                         num_heads=2, mlp_dim=32, max_seq_len=SEQ,
                         dropout_rate=0.0, moe_experts=4)
        mesh = make_mesh({"dp": 2, "pp": 2, "ep": 2})
        t = dk.PipelineTrainer(
            _make(cfg, SEQ, f"bert_moe1f1b_{schedule}"),
            worker_optimizer="adam", learning_rate=3e-3,
            num_stages=2, num_microbatches=2, batch_size=16,
            num_epoch=2, seed=0, schedule=schedule, mesh=mesh, ep=2,
            aux_loss_weight=0.05,
        )
        t.train(ds, shuffle=True)
        return t.get_history()

    h1, h2 = run("1f1b"), run("gpipe")
    assert len(h1) == len(h2)
    assert h1[-1]["loss"] < h1[0]["loss"]
    for a, b in zip(h1, h2):
        assert abs(a["loss"] - b["loss"]) < 2e-3, (a, b)
        assert abs(a["aux_loss"] - b["aux_loss"]) < 2e-2, (a, b)


def test_1f1b_single_microbatch_edge():
    """M=1 leaves the steady phase empty (the scan split elides the fill
    phase's cotangent hops and the drain phase's activation hops — VERDICT
    r4 weak #5); parity must survive the empty middle scan."""
    stages, head, mb, labels = _setup(M=1)
    mesh = make_mesh({"pp": P_DEV})
    stacked = stack_stage_params(stages)
    loss, sg, hg, cot = jax.jit(
        lambda s, h, x, y: pipeline_1f1b_value_and_grad(
            _stage_fn, _last_fn, s, h, x, y, mesh
        )
    )(stacked, head, mb, labels)
    ref_loss, ref_sg_list = jax.value_and_grad(
        lambda s: _sequential_loss(s, head, jnp.asarray(mb), labels)
    )(stages)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for i in range(P_DEV):
        np.testing.assert_allclose(
            np.asarray(sg["w"][i]), np.asarray(ref_sg_list[i]["w"]),
            atol=1e-4, rtol=1e-4,
        )


@pytest.mark.slow
def test_pipeline_trainer_1f1b_moe_ep_with_dropout_trains():
    """MoE x ep x dropout through the hand-rolled schedule: the B-tick
    recompute must reproduce the F-tick's dropout masks with the aux
    channel active (deterministic per-(m, stage, dp) keys), or gradients
    silently mismatch the forward — caught here as training failing to
    converge."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.bert import BertConfig, _make

    VOCAB, SEQ = 32, 8
    cfg = BertConfig(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                     num_heads=2, mlp_dim=32, max_seq_len=SEQ,
                     dropout_rate=0.1, moe_experts=4)
    rng = np.random.default_rng(7)
    x = rng.integers(0, VOCAB, size=(96, SEQ)).astype(np.int32)
    ds = dk.Dataset.from_arrays(features=x, label=x.copy())
    mesh = make_mesh({"dp": 2, "pp": 2, "ep": 2})
    t = dk.PipelineTrainer(
        _make(cfg, SEQ, "bert_moe1f1b_drop"), num_stages=2, ep=2,
        schedule="1f1b", num_microbatches=2, batch_size=16,
        num_epoch=4, learning_rate=3e-3, worker_optimizer="adam", seed=0,
        mesh=mesh, aux_loss_weight=0.05,
    )
    t.train(ds, shuffle=True)
    h = t.get_history()
    # Same bar as the non-moe dropout test: monotone-ish improvement (a
    # mask mismatch between F-tick and B-tick recompute stalls training
    # entirely — measured here as loss 3.47->2.94, acc 0.05->0.27).
    assert h[-1]["loss"] < h[0]["loss"], (h[0], h[-1])
    assert all(np.isfinite(s["aux_loss"]) for s in h)
    assert "accuracy" in h[-1] and h[-1]["accuracy"] > h[0]["accuracy"]


def test_1f1b_phase_split_compiles_dead_hops_away():
    """Structural pin for the hop elision: the compiled step must contain
    THREE scan loops with FOUR collective-permute sites total (fill: act
    only; steady: act+cot; drain: cot only) — a regression that merges the
    phases back into one loop, or re-adds a dead hop, changes the count."""
    stages, head, mb, labels = _setup()
    mesh = make_mesh({"pp": P_DEV})
    stacked = stack_stage_params(stages)
    txt = jax.jit(
        lambda s, h, x, y: pipeline_1f1b_value_and_grad(
            _stage_fn, _last_fn, s, h, x, y, mesh
        )
    ).lower(stacked, head, mb, labels).compile().as_text()
    hops = txt.count("collective-permute(") + txt.count(
        "collective-permute-start("
    )
    # Inequalities, not exact pins: XLA upgrades may fuse loops, unroll
    # short scans, or rename collective ops, and that must not false-fail
    # this test. The regressions it guards still trip the bounds — a
    # re-added dead hop pushes sites above 4; merging the fill/steady/
    # drain phases back into one scan drops the loop count below 2.
    assert 1 <= hops <= 4, f"expected <=4 ppermute sites (1+2+1), found {hops}"
    assert 2 <= txt.count("while(") <= 3, "expected the split phase scans"
