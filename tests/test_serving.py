"""Continuous-batching serving engine (distkeras_tpu.serving).

The invariants under test, all on CPU with a tiny causal LM:

- greedy streams match one-shot ``generate()`` token-for-token even when
  requests are admitted mid-decode into freed slots;
- admission never retraces the decode step (compile-count probe stays 1);
- slot admit/free bookkeeping (active count bounded, slots reused, all
  free after drain);
- backpressure (``QueueFullError`` at max depth), deadline expiry
  (``RequestTimeout`` queued AND mid-decode), graceful-shutdown drain
  (``EngineStopped`` for the queue, completion for in-flight slots);
- scheduler ordering (priority-FIFO) and the TCP server/client wire.
"""

import asyncio

import numpy as np
import pytest

from distkeras_tpu.inference.generate import generate
from distkeras_tpu.models.bert import gpt_tiny
from distkeras_tpu.serving import (
    EngineStopped,
    QueueFullError,
    Request,
    RequestCancelled,
    RequestTimeout,
    Scheduler,
    ServingClient,
    ServingEngine,
    ServingMetrics,
    ServingServer,
)

VOCAB = 64


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(0)


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


def _want(lm, prompt, n):
    model, variables = lm
    return generate(model, variables, np.asarray([prompt], np.int32), n,
                    greedy=True)[0].tolist()


async def _run_engine(engine, coro):
    """Drive ``engine.run()`` alongside ``coro``; shuts down on exit."""
    task = asyncio.create_task(engine.run())
    try:
        return await coro
    finally:
        engine.shutdown(drain=True)
        await task


# -- scheduler unit behavior -------------------------------------------------

def test_scheduler_priority_fifo_and_backpressure():
    async def go():
        s = Scheduler(max_depth=3)
        a = Request([1], 1, priority=1)
        b = Request([2], 1, priority=0)
        c = Request([3], 1, priority=1)
        for r in (a, b, c):
            s.submit(r)
        with pytest.raises(QueueFullError):
            s.submit(Request([4], 1))
        # b first (lower priority value), then a before c (FIFO in tie).
        assert s.pop() is b and s.pop() is a and s.pop() is c
        assert s.pop() is None

    asyncio.run(go())


def test_scheduler_peek_is_nondestructive_head():
    """peek() shows the next pop without consuming it — the paged
    engine's admission-park gate watches it so a parked head that is
    displaced (higher-priority arrival, cancel/expire) reopens
    admission without waiting for the pool version to move."""
    async def go():
        s = Scheduler(max_depth=4)
        assert s.peek() is None
        a = Request([1], 1, priority=1)
        s.submit(a)
        assert s.peek() is a and s.peek() is a  # non-destructive
        b = Request([2], 1, priority=0)
        s.submit(b)
        assert s.peek() is b  # higher priority displaced the head
        assert s.pop() is b and s.peek() is a

    asyncio.run(go())


def test_scheduler_expires_queued_deadlines():
    async def go():
        s = Scheduler(max_depth=4)
        fast = Request([1], 1, timeout=0.0)
        slow = Request([2], 1)
        s.submit(fast, now=100.0)
        s.submit(slow, now=100.0)
        expired = s.expire(now=101.0)
        assert expired == [fast]
        assert s.pop(now=101.0) is slow

    asyncio.run(go())


# -- engine core -------------------------------------------------------------

def test_continuous_batching_parity_and_single_compile(lm, rng):
    """Staggered submissions through fewer slots than requests: later
    requests are admitted into freed slots while earlier ones decode, and
    every greedy stream still matches one-shot generate()."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=2, max_queue=8)
    prompts = [_prompt(rng, n) for n in (5, 9, 3, 7, 4)]

    async def work():
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(engine.submit(p, 6))
            await asyncio.sleep(0.01 * i)  # arrive mid-decode
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(engine, work()))
    for p, got in zip(prompts, outs):
        assert got == _want(lm, p, 6)
    # 5 requests through 2 slots admitted mid-decode: still ONE compiled
    # decode executable (continuous batching never retraces). -1 means
    # the probe's private jax attribute vanished in an upgrade — tolerate
    # it (same contract as serving_bench) rather than false-failing.
    assert engine.decode_compile_count() in (1, -1)
    # Slot bookkeeping: everything freed after the drain.
    assert engine.active_slots == 0
    assert all(s is None for s in engine._slot_state)


def test_slot_reuse_and_occupancy_bound(lm, rng):
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1, max_queue=8)

    async def work():
        r1 = engine.submit(_prompt(rng, 4), 3)
        r2 = engine.submit(_prompt(rng, 6), 3)
        o1, o2 = await r1.result(), await r2.result()
        return o1, o2

    o1, o2 = asyncio.run(_run_engine(engine, work()))
    assert len(o1) == 3 and len(o2) == 3
    # One slot served both sequentially; occupancy never exceeded 1 slot.
    assert engine.metrics.completed == 2
    assert max(engine.metrics._occupancy) <= 1.0


def test_backpressure_rejects_with_typed_error(lm, rng):
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1, max_queue=2)
    # No run() loop: the queue only fills. max_queue=2 admits two, the
    # third is shed BEFORE any device work, with the typed error.
    engine.submit(_prompt(rng, 3), 2)
    engine.submit(_prompt(rng, 3), 2)
    with pytest.raises(QueueFullError):
        engine.submit(_prompt(rng, 3), 2)
    assert engine.metrics.rejected == 1


def test_submit_validates_before_queueing(lm):
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1)
    with pytest.raises(ValueError, match="trained context"):
        engine.submit(list(range(28)), 8)  # 28 + 8 > 32
    with pytest.raises(ValueError, match="non-empty"):
        engine.submit([], 4)
    assert len(engine.scheduler) == 0


def test_timeout_expires_queued_request(lm, rng):
    """A request whose deadline passes while WAITING for a slot gets
    RequestTimeout, while the slot-holder completes normally."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1, max_queue=4)

    async def work():
        long_req = engine.submit(_prompt(rng, 4), 10)
        doomed = engine.submit(_prompt(rng, 4), 2, timeout=0.0)
        out = await long_req.result()
        with pytest.raises(RequestTimeout):
            await doomed.result()
        return out

    out = asyncio.run(_run_engine(engine, work()))
    assert len(out) == 10
    assert engine.metrics.expired == 1


def test_timeout_expires_mid_decode(lm, rng):
    """A deadline passing mid-generation frees the slot early: the stream
    ends in RequestTimeout after at least the prefill token arrived."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1)

    async def work():
        req = engine.submit(_prompt(rng, 4), 28)
        # Wait until admitted (first token streamed), then move the
        # deadline into the past — deterministic mid-decode expiry with
        # no dependence on this machine's decode-step wall time.
        kind, _ = await req.events.get()
        assert kind == "token"
        req.timeout = -1.0
        with pytest.raises(RequestTimeout):
            await req.result()
        return req

    req = asyncio.run(_run_engine(engine, work()))
    assert 1 <= len(req.out_tokens) < 28
    assert engine.active_slots == 0


def test_cancel_frees_slot_mid_decode_and_in_queue(lm, rng):
    """cancel() releases a held slot (client-disconnect path) so queued
    work takes it over, and drops a still-queued request."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1, max_queue=4)

    waiting_prompt = _prompt(rng, 5)

    async def work():
        holder = engine.submit(_prompt(rng, 4), 28)
        kind, _ = await holder.events.get()
        assert kind == "token"  # holder owns the slot
        waiting = engine.submit(waiting_prompt, 3)
        doomed = engine.submit(_prompt(rng, 3), 3)
        holder.cancel()
        doomed.cancel()
        out = await waiting.result()  # takes over the freed slot
        with pytest.raises(RequestCancelled):
            await holder.result()
        with pytest.raises(RequestCancelled):
            await doomed.result()
        return out

    out = asyncio.run(_run_engine(engine, work()))
    assert out == _want(lm, waiting_prompt, 3)
    assert engine.active_slots == 0


def test_graceful_shutdown_drains_active_rejects_queued(lm, rng):
    model, variables = lm
    engine = ServingEngine(model, variables, slots=1, max_queue=4)

    async def go():
        task = asyncio.create_task(engine.run())
        active = engine.submit(_prompt(rng, 4), 8)
        # Wait for admission (first token) so `active` holds the slot.
        kind, _ = await active.events.get()
        assert kind == "token"
        queued = engine.submit(_prompt(rng, 4), 4)
        engine.shutdown(drain=True)
        with pytest.raises(EngineStopped):
            engine.submit(_prompt(rng, 3), 2)  # late arrival: typed reject
        out = await active.result()  # drained to completion
        with pytest.raises(EngineStopped):
            await queued.result()  # queued work is shed
        await task
        return out

    out = asyncio.run(go())
    assert len(out) == 8
    assert engine.active_slots == 0


def test_sampled_and_greedy_coexist_one_program(lm, rng):
    """temperature>0 rows sample, temperature<=0 rows stay argmax — in
    the same compiled step (no retrace between them)."""
    model, variables = lm
    engine = ServingEngine(model, variables, slots=2, seed=3)
    p = _prompt(rng, 5)

    async def work():
        greedy = engine.submit(p, 6)
        hot = engine.submit(p, 6, temperature=5.0)
        return await greedy.result(), await hot.result()

    g, h = asyncio.run(_run_engine(engine, work()))
    assert g == _want(lm, p, 6)
    assert all(0 <= t < VOCAB for t in h)
    assert engine.decode_compile_count() in (1, -1)


def test_metrics_summary_and_stream(lm, rng, tmp_path):
    import json

    from distkeras_tpu.tracing import MetricStream

    model, variables = lm
    path = tmp_path / "serving.jsonl"
    metrics = ServingMetrics(MetricStream.to_jsonl(str(path)))
    engine = ServingEngine(model, variables, slots=2, metrics=metrics)

    async def work():
        reqs = [engine.submit(_prompt(rng, n), 4) for n in (3, 5, 4)]
        return [await r.result() for r in reqs]

    asyncio.run(_run_engine(engine, work()))
    s = metrics.emit_summary()
    for key in ("ttft_p50_s", "ttft_p95_s", "ttft_p99_s",
                "inter_token_p50_s", "tokens_per_sec",
                "slot_occupancy_mean"):
        assert key in s, key
    assert s["requests_completed"] == 3
    assert s["tokens_out"] == 12
    lines = [json.loads(l) for l in open(path)]
    # Per-iteration series plus the final summary record.
    assert any("summary" in rec for rec in lines)
    assert any("queue_depth" in rec for rec in lines)


# -- chunked prefill + prefix cache ------------------------------------------

def test_chunked_prefill_parity_and_ttft_split(lm, rng):
    """Chunked admission (one prefill chunk per decode tick) is greedy
    token-identical to generate(), never retraces the armed decode step,
    and records the TTFT split (admission wait vs prefill device time)."""
    from distkeras_tpu.telemetry import RecompileAuditor

    model, variables = lm
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=2, max_queue=8,
                           prefill_chunk=4, auditor=auditor,
                           arm_auditor_after_warmup=True)
    prompts = [_prompt(rng, n) for n in (13, 5, 9, 3, 11)]

    async def work():
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(engine.submit(p, 5))
            await asyncio.sleep(0.01 * i)
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(engine, work()))
    for p, got in zip(prompts, outs):
        assert got == _want(lm, p, 5)
    assert auditor.compiles("serving_decode") == 1
    assert engine.decode_compile_count() in (1, -1)
    s = engine.metrics.summary()
    # 13-token prompt through 4-token chunks = 4 chunks.
    assert s["prefill_chunks_max"] == 4.0
    # The split: both halves of TTFT recorded per request.
    assert s["prefill_device_p50_s"] > 0
    assert "queue_wait_p50_s" in s
    snap = engine.metrics.registry.snapshot()
    assert snap["serving_prefill_device_seconds"]["count"] == len(prompts)
    assert snap["serving_queue_wait_seconds"]["count"] == len(prompts)


def test_prefix_cache_hits_are_parity_exact_vs_monolithic_and_generate(
        lm, rng):
    """THE satellite invariant: chunked + prefix-cached admission is
    token-identical to monolithic prefill and to offline generate(),
    and repeat prompts actually hit (matched tokens recorded)."""
    model, variables = lm
    cached = ServingEngine(model, variables, slots=1, max_queue=16,
                           prefill_chunk=4, prefix_cache_mb=1.0,
                           prefix_block_tokens=4)
    plain = ServingEngine(model, variables, slots=1, max_queue=16)
    shared = _prompt(rng, 12)
    prompts = [shared + _prompt(rng, k) for k in (3, 4, 5, 3)]

    async def drive(engine):
        outs = []
        for p in prompts:  # sequential: later prompts can hit earlier ones
            outs.append(await engine.submit(p, 5).result())
        return outs

    got_cached = asyncio.run(_run_engine(cached, drive(cached)))
    got_plain = asyncio.run(_run_engine(plain, drive(plain)))
    want = [_want(lm, p, 5) for p in prompts]
    assert got_cached == want  # vs offline generate()
    assert got_plain == want  # monolithic == chunked+cached == generate
    stats = cached.prefix_cache.stats()
    assert stats["hit_requests"] >= 3  # every repeat matched the prefix
    assert stats["hit_tokens"] >= 3 * 12
    assert cached.decode_compile_count() in (1, -1)
    assert cached.metrics.summary()["prefix_hit_rate"] > 0.4


def test_prefix_cache_hit_after_evict_round_trip(lm, rng):
    """Evicting a cached prefix must only cost performance, never
    correctness: A cached -> A evicted by B (tiny budget) -> A re-prefilled
    from scratch and re-cached -> A hits again; parity holds throughout."""
    from distkeras_tpu.serving import PrefixCache

    model, variables = lm
    probe_engine = ServingEngine(model, variables, slots=1)
    probe = PrefixCache(probe_engine._row_shapes, block_tokens=4,
                        budget_bytes=1 << 20)
    pc = PrefixCache(probe_engine._row_shapes, block_tokens=4,
                     budget_bytes=2 * probe.bytes_per_block)  # 2 blocks
    engine = ServingEngine(model, variables, slots=1, max_queue=16,
                           prefix_cache=pc)
    a, b = _prompt(rng, 11), _prompt(rng, 11)

    async def drive():
        outs = []
        for p in (a, a, b, a, a):  # hit, evict via b, miss, re-hit
            outs.append(await engine.submit(p, 4).result())
        return outs

    outs = asyncio.run(_run_engine(engine, drive()))
    wa, wb = _want(lm, a, 4), _want(lm, b, 4)
    assert outs == [wa, wa, wb, wa, wa]
    s = pc.stats()
    assert s["evicted_blocks"] > 0  # b really displaced a
    assert s["hit_requests"] >= 2  # the 2nd a (pre-evict) + 5th (post)
    assert s["blocks_used"] <= 2  # budget held


def test_prefill_bucket_never_overshoots_headroom_free_cache(rng):
    """Regression: with max_seq_len == trained length (no accidental
    cache headroom) a hit's tail bucket must be capped at the remaining
    cache room — an overshooting pad width would make the per-slot KV
    write clamp backward over the spliced prefix and silently corrupt
    output. Covers both monolithic and chunked ragged-final-chunk
    paths."""
    model = gpt_tiny(seq_len=64, vocab_size=VOCAB)
    variables = model.init(0)
    pre = _prompt(rng, 8)
    long_tail = pre + _prompt(rng, 41)  # matched 8 + tail 41 -> bucket 64

    for kwargs in ({}, {"prefill_chunk": 48}):
        engine = ServingEngine(model, variables, slots=1, max_queue=8,
                               prefix_cache_mb=1.0, prefix_block_tokens=8,
                               **kwargs)

        async def drive():
            outs = []
            for p in (pre + _prompt(rng, 2), long_tail):  # cache, then hit
                outs.append(await engine.submit(p, 4).result())
            return outs

        outs = asyncio.run(_run_engine(engine, drive()))
        assert engine.prefix_cache.stats()["hit_tokens"] >= 8
        want = generate(model, variables,
                        np.asarray([long_tail], np.int32), 4,
                        greedy=True)[0].tolist()
        assert outs[1] == want, f"corrupted hit output with {kwargs}"


def test_scheduler_cache_aware_pop_prefers_hits_within_class():
    async def go():
        scores = {(7, 7): 8, (1, 1): 0, (2, 2): 4}
        s = Scheduler(max_depth=8,
                      cache_probe=lambda p: scores.get(tuple(p), 0))
        cold = Request([1, 1], 1)
        warm = Request([7, 7], 1)
        lukewarm = Request([2, 2], 1)
        urgent = Request([1, 1], 1, priority=-1)
        for r in (cold, warm, lukewarm):
            s.submit(r)
        # Best hit first within the class; FIFO among the rest.
        assert s.pop() is warm
        s.submit(urgent)
        # A better-priority class is NEVER jumped by a cache hit.
        assert s.pop() is urgent
        assert s.pop() is lukewarm and s.pop() is cold
        # Without a probe, pure priority-FIFO (regression guard).
        s2 = Scheduler(max_depth=4)
        x, y = Request([7, 7], 1), Request([1, 1], 1)
        s2.submit(x)
        s2.submit(y)
        assert s2.pop() is x and s2.pop() is y
        # Starvation bound: a cold head under sustained warm traffic is
        # served once its overtake budget is exhausted.
        s3 = Scheduler(max_depth=16, cache_probe=lambda p: p[0])
        cold3 = Request([0], 1)
        s3.submit(cold3)
        for _ in range(s3.max_overtake):
            s3.submit(Request([9], 1))
            assert s3.pop() is not cold3  # warm hit jumps ahead
        s3.submit(Request([9], 1))
        assert s3.pop() is cold3  # budget spent: FIFO wins

    asyncio.run(go())


# -- telemetry integration ---------------------------------------------------

def test_recompile_auditor_armed_is_runtime_invariant(lm, rng):
    """THE engine invariant, as a runtime check instead of a benchmark
    assertion: with the auditor armed after the first decode iteration,
    admissions into freed slots mid-decode must not retrace the decode
    step — any retrace would raise RecompileError and fail this test."""
    from distkeras_tpu.telemetry import RecompileAuditor

    model, variables = lm
    auditor = RecompileAuditor()
    engine = ServingEngine(model, variables, slots=2, max_queue=8,
                           auditor=auditor, arm_auditor_after_warmup=True)
    prompts = [_prompt(rng, n) for n in (5, 9, 3, 7, 4)]

    async def work():
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(engine.submit(p, 6))
            await asyncio.sleep(0.01 * i)  # arrive mid-decode, post-arming
        return [await r.result() for r in reqs]

    outs = asyncio.run(_run_engine(engine, work()))
    for p, got in zip(prompts, outs):
        assert got == _want(lm, p, 6)
    # Armed + completed == the invariant held at runtime; the counts agree.
    assert auditor.compiles("serving_decode") == 1
    assert auditor.report()["serving_decode"]["armed"]
    assert engine.decode_compile_count() in (1, -1)
    # The admit splice compiles at most once per process: it wraps the
    # module-level _admit_fn, so jax shares its executable cache across
    # engines — an earlier engine in this test session may have already
    # paid the one compile (0 new compiles here is the cache working).
    assert auditor.compiles("serving_admit") <= 1
    assert auditor.report()["serving_admit"]["calls"] == len(prompts)


def test_engine_spans_export_chrome_trace(lm, rng):
    """A traced serving run yields one Perfetto-loadable timeline:
    admit/prefill/decode_tick spans present, B/E matched per lane even
    though engine iterations and client tasks interleave on one loop."""
    import distkeras_tpu.telemetry as T

    model, variables = lm
    tracer = T.enable_tracing()
    try:
        engine = ServingEngine(model, variables, slots=2)

        async def work():
            reqs = [engine.submit(_prompt(rng, n), 4) for n in (3, 6)]
            return [await r.result() for r in reqs]

        asyncio.run(_run_engine(engine, work()))
    finally:
        T.disable_tracing()
    trace = tracer.chrome_trace()
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "B"}
    assert {"admit", "prefill", "decode_tick", "stream"} <= names
    # Matched B/E per lane (the Perfetto structural requirement).
    stacks = {}
    for ev in trace["traceEvents"]:
        if ev["ph"] == "B":
            stacks.setdefault(ev["tid"], []).append(ev["name"])
        elif ev["ph"] == "E":
            assert stacks.get(ev["tid"]), "E without matching B"
            assert stacks[ev["tid"]].pop() == ev["name"]
    assert all(not s for s in stacks.values())
    # prefill nests under admit (executor thread lane tracks the caller's
    # context because contextvars flow into run_in_executor).
    prefill_b = next(e for e in trace["traceEvents"]
                     if e["ph"] == "B" and e["name"] == "prefill")
    assert prefill_b["args"]["parent"] == "admit"


def test_serving_metrics_publish_to_registry(lm, rng):
    model, variables = lm
    engine = ServingEngine(model, variables, slots=2)

    async def work():
        reqs = [engine.submit(_prompt(rng, n), 4) for n in (3, 5)]
        return [await r.result() for r in reqs]

    asyncio.run(_run_engine(engine, work()))
    snap = engine.metrics.registry.snapshot()
    assert snap["serving_requests_completed_total"]["value"] == 2
    assert snap["serving_tokens_out_total"]["value"] == 8
    assert snap["serving_ttft_seconds"]["count"] == 2
    assert snap["scheduler_submitted_total"]["value"] == 2
    # Counter compatibility surface still reads through.
    assert engine.metrics.completed == 2 and engine.metrics.tokens_out == 8


# -- TCP front end -----------------------------------------------------------

def test_tcp_server_streams_and_matches_generate(lm, rng):
    model, variables = lm
    p1, p2 = _prompt(rng, 6), _prompt(rng, 4)

    async def go():
        engine = ServingEngine(model, variables, slots=2)
        server = ServingServer(engine, port=0)
        await server.start()

        async def one(p):
            streamed = []
            async with ServingClient("127.0.0.1", server.port) as c:
                done = await c.generate(p, 5, on_token=streamed.append)
            return streamed, done

        (s1, d1), (s2, d2) = await asyncio.gather(one(p1), one(p2))
        await server.stop(drain=True)
        return (s1, d1), (s2, d2)

    (s1, d1), (s2, d2) = asyncio.run(go())
    assert s1 == d1["tokens"] == _want(lm, p1, 5)
    assert s2 == d2["tokens"] == _want(lm, p2, 5)
    assert d1["ttft_ms"] > 0 and d1["latency_ms"] >= d1["ttft_ms"]


def test_tcp_server_metricsz_and_healthz_verbs(lm, rng):
    """Live metrics exposition over the existing JSONL protocol: one
    request line in, one reply line out — JSON snapshot, the Prometheus
    text page, and the engine health view."""
    model, variables = lm

    async def go():
        engine = ServingEngine(model, variables, slots=2)
        server = ServingServer(engine, port=0)
        await server.start()
        async with ServingClient("127.0.0.1", server.port) as c:
            await c.generate(_prompt(rng, 4), 3)
            snap = await c.metricsz()
            prom = await c.metricsz(format="prometheus")
            health = await c.healthz()
            c._writer.write(b'{"cmd": "nope"}\n')
            await c._writer.drain()
            import json as _json

            bad = _json.loads(await c._reader.readline())
            # The connection still serves generation after control verbs.
            toks = [t async for t in c.stream(_prompt(rng, 3), 2)]
        await server.stop(drain=True)
        return snap, prom, health, bad, toks

    snap, prom, health, bad, toks = asyncio.run(go())
    assert snap["serving_requests_completed_total"]["value"] == 1
    assert snap["serving_ttft_seconds"]["count"] == 1
    assert "# TYPE serving_ttft_seconds histogram" in prom
    assert "serving_requests_completed_total 1" in prom
    assert health["slots"] == 2 and health["active_slots"] == 0
    assert health["decode_compile_count"] in (1, -1)
    assert bad["code"] == "bad_request"
    assert len(toks) == 2


def test_server_stop_drain_completes_inflight_rejects_new(lm, rng):
    """THE contract the cluster's rolling reload stands on: during
    ``server.stop(drain=True)`` a mid-stream request runs to completion
    (its tokens keep flowing and match generate()) while new admissions
    on already-open connections are rejected with the typed ``stopped``
    error."""
    model, variables = lm
    p = _prompt(rng, 4)

    async def go():
        engine = ServingEngine(model, variables, slots=1)
        server = ServingServer(engine, port=0)
        await server.start()
        streamer = ServingClient("127.0.0.1", server.port)
        late = ServingClient("127.0.0.1", server.port)
        await streamer.connect()
        await late.connect()  # connected BEFORE the listener closes
        stream = streamer.stream(p, 12)
        first = await stream.__anext__()  # admitted, mid-stream
        stop_task = asyncio.create_task(server.stop(drain=True))
        await asyncio.sleep(0)  # let stop() close admission
        # A new request over the still-open connection is shed with the
        # typed error, not a hang and not a dropped connection.
        with pytest.raises(EngineStopped):
            await late.generate(_prompt(rng, 3), 2)
        # The in-flight stream drains to its full length.
        toks = [first] + [t async for t in stream]
        await streamer.aclose()
        await late.aclose()
        await stop_task
        return toks

    toks = asyncio.run(go())
    assert toks == _want(lm, p, 12)


def test_client_control_verbs_reconnect_with_backoff(lm, rng):
    """metricsz/healthz survive a server bounce: the client drops its
    dead connection and redials with capped backoff (RetryingClient's
    pattern) instead of surfacing a raw ConnectionResetError; the budget
    exhausts into a typed ConnectionError when nobody is listening."""
    model, variables = lm

    async def go():
        engine = ServingEngine(model, variables, slots=1)
        server = ServingServer(engine, port=0)
        await server.start()
        port = server.port
        client = ServingClient("127.0.0.1", port, base_delay_s=0.01)
        h1 = await client.healthz()  # pins a live connection
        await server.stop(drain=True)
        # Same-port restart — a replica bounce as a monitor would see it.
        server2 = ServingServer(
            ServingEngine(model, variables, slots=1), port=port)
        await server2.start()
        h2 = await client.healthz()  # stale conn -> reconnect -> answer
        await server2.stop(drain=True)
        # stop() only closes the LISTENER; drop our live connection so
        # the next verb must redial a port nobody listens on.
        await client.aclose()
        with pytest.raises(ConnectionError, match="healthz"):
            await client.healthz()  # budget exhausts into a typed error
        return h1, h2

    h1, h2 = asyncio.run(go())
    assert h1["slots"] == 1 and h2["slots"] == 1


def test_server_reload_verb_swaps_weights(lm, rng, tmp_path):
    """The replica-side half of the rolling reload: the ``reload`` verb
    hot-swaps params from a weights file on a live server — outputs
    before match the old weights, after match the new, and bad input
    fails typed without disturbing serving."""
    from distkeras_tpu.checkpoint import save_weights_file
    from distkeras_tpu.serving.client import ServerError

    model, variables = lm
    new_vars = model.init(7)
    path = str(tmp_path / "w.bin")
    save_weights_file(path, new_vars)
    p = _prompt(rng, 5)

    async def go():
        engine = ServingEngine(model, variables, slots=2)
        server = ServingServer(engine, port=0)
        await server.start()
        async with ServingClient("127.0.0.1", server.port) as c:
            before = (await c.generate(p, 4))["tokens"]
            rep = await c.reload(path)
            after = (await c.generate(p, 4))["tokens"]
            with pytest.raises(ServerError):
                await c.reload(str(tmp_path / "missing.bin"))
            still = (await c.generate(p, 4))["tokens"]
        await server.stop(drain=True)
        return before, rep, after, still

    before, rep, after, still = asyncio.run(go())
    assert rep["ok"]
    assert before == _want(lm, p, 4)
    want_new = generate(model, new_vars, np.asarray([p], np.int32), 4,
                        greedy=True)[0].tolist()
    assert after == still == want_new


def test_tcp_server_rejects_bad_and_overflow_requests(lm, rng):
    model, variables = lm

    async def go():
        engine = ServingEngine(model, variables, slots=1, max_queue=1)
        server = ServingServer(engine, port=0)
        await server.start()
        codes = []
        async with ServingClient("127.0.0.1", server.port) as c:
            # Context overflow -> bad_request (ValueError server-side).
            c._writer.write(b'{"prompt": [1], "max_new_tokens": 99}\n')
            await c._writer.drain()
            import json as _json

            codes.append(_json.loads(await c._reader.readline()).get("code"))
            # Malformed -> bad_request.
            c._writer.write(b'{"max_new_tokens": 2}\n')
            await c._writer.drain()
            codes.append(_json.loads(await c._reader.readline()).get("code"))
            # Uncastable timeout -> bad_request at submit, NOT a TypeError
            # later inside the engine loop's deadline arithmetic (which
            # would kill serving for every connection).
            c._writer.write(
                b'{"prompt": [1], "max_new_tokens": 2, "timeout": "zzz"}\n')
            await c._writer.drain()
            codes.append(_json.loads(await c._reader.readline()).get("code"))
            # The engine survived: a well-formed request still completes.
            toks = [t async for t in c.stream([1, 2], 2)]
        await server.stop()
        return codes, toks

    codes, toks = asyncio.run(go())
    assert codes == ["bad_request", "bad_request", "bad_request"]
    assert len(toks) == 2
