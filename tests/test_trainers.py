"""End-to-end trainer tests on CPU (8 virtual devices).

Mirrors the reference's acceptance criterion (SURVEY §4): "does accuracy come
out ≈ the single-node run" on a small problem, for every trainer in the zoo.
"""

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.models.core import Model


def _model(input_dim=16, classes=2):
    return Model.from_flax(
        MLP(features=(32,), num_classes=classes),
        input_shape=(input_dim,),
        output_dim=classes,
    )


def _accuracy(trained, ds):
    predictor = dk.ModelPredictor(trained)
    out = predictor.predict(ds)
    out = dk.LabelIndexTransformer(input_col="prediction").transform(out)
    return dk.AccuracyEvaluator(
        prediction_col="prediction_index", label_col="label"
    ).evaluate(out)


def test_single_trainer_learns(toy_classification):
    trainer = dk.SingleTrainer(
        _model(), worker_optimizer="adam", loss="categorical_crossentropy",
        batch_size=32, num_epoch=8, learning_rate=0.01,
    )
    trained = trainer.train(toy_classification)
    acc = _accuracy(trained, toy_classification)
    assert acc > 0.9, f"single trainer failed to learn: acc={acc}"
    assert trainer.get_training_time() > 0
    assert len(trainer.get_history()) == (512 // 32) * 8
    assert "loss" in trainer.get_history()[0]


def test_single_trainer_multiclass(toy_multiclass):
    trainer = dk.SingleTrainer(
        _model(input_dim=20, classes=4), worker_optimizer="adam", learning_rate=0.01,
        batch_size=32, num_epoch=6,
    )
    trained = trainer.train(toy_multiclass, shuffle=True)
    assert _accuracy(trained, toy_multiclass) > 0.85


@pytest.mark.parametrize(
    "cls,kwargs",
    [
        (dk.DOWNPOUR, dict(communication_window=4)),
        pytest.param(dk.ADAG, dict(communication_window=4), marks=pytest.mark.slow),
        pytest.param(dk.AEASGD, dict(communication_window=4, rho=2.0, learning_rate=0.05), marks=pytest.mark.slow),
        pytest.param(dk.EAMSGD, dict(communication_window=4, rho=2.0, learning_rate=0.05, momentum=0.8), marks=pytest.mark.slow),
        pytest.param(dk.DynSGD, dict(communication_window=4), marks=pytest.mark.slow),
    ],
)
def test_async_trainers_learn(toy_classification, cls, kwargs):
    kwargs.setdefault("learning_rate", 0.01)
    trainer = cls(
        _model(), worker_optimizer="adam", loss="categorical_crossentropy",
        num_workers=4, batch_size=16, num_epoch=6, **kwargs,
    )
    trained = trainer.train(toy_classification)
    acc = _accuracy(trained, toy_classification)
    assert acc > 0.85, f"{cls.__name__} failed to learn: acc={acc}"
    # PS actually saw traffic
    assert trainer.parameter_server.num_commits > 0
    # history tagged per worker
    workers = {h["worker"] for h in trainer.get_history()}
    assert workers == {0, 1, 2, 3}


def test_sync_distributed_trainer(toy_classification):
    trainer = dk.SynchronousDistributedTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01, num_workers=8, batch_size=8,
        num_epoch=6,
    )
    trained = trainer.train(toy_classification)
    assert _accuracy(trained, toy_classification) > 0.9


def test_averaging_trainer(toy_classification):
    trainer = dk.AveragingTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01, num_workers=4, batch_size=16,
        num_epoch=6,
    )
    trained = trainer.train(toy_classification)
    assert _accuracy(trained, toy_classification) > 0.8


def test_ensemble_trainer(toy_classification):
    trainer = dk.EnsembleTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01, num_models=3, batch_size=16,
        num_epoch=6,
    )
    models = trainer.train(toy_classification)
    assert len(models) == 3
    for m in models:
        assert _accuracy(m, toy_classification) > 0.75
    # replicas are actually different models (different init seeds)
    w0 = models[0].params["Dense_0"]["kernel"]
    w1 = models[1].params["Dense_0"]["kernel"]
    assert not np.allclose(w0, w1)


@pytest.mark.slow
def test_async_trainer_parallelism_factor(toy_classification):
    trainer = dk.DOWNPOUR(
        _model(), worker_optimizer="adam", learning_rate=0.01, num_workers=2, batch_size=16,
        num_epoch=2, communication_window=3, parallelism_factor=2,
    )
    trained = trainer.train(toy_classification)
    assert _accuracy(trained, toy_classification) > 0.7


def test_ensemble_replicas_sharded_over_devices(toy_classification):
    """8 replicas on 8 devices: the replica axis is device-sharded."""
    trainer = dk.EnsembleTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01, num_models=8,
        batch_size=8, num_epoch=2,
    )
    models = trainer.train(toy_classification)
    assert len(models) == 8
    accs = [_accuracy(m, toy_classification) for m in models]
    assert min(accs) > 0.6, accs


def test_remat_step_matches_plain(toy_classification):
    """remat=True recomputes activations but must be numerically identical."""
    import optax
    from distkeras_tpu.training.step import TrainState, make_train_step

    model = _model()
    opt = optax.sgd(0.05)
    s0 = TrainState.create(model, opt, rng=0)
    batch = {
        "features": toy_classification["features"][:32],
        "label": toy_classification["label"][:32],
    }
    plain = make_train_step(model, opt, "categorical_crossentropy", donate=False)
    remat = make_train_step(model, opt, "categorical_crossentropy", donate=False, remat=True)
    s1, m1 = plain(s0, batch)
    s2, m2 = remat(s0, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(s1.params["Dense_0"]["kernel"]),
        np.asarray(s2.params["Dense_0"]["kernel"]),
        atol=1e-6,
    )


def test_sync_trainer_fsdp_mesh(toy_classification):
    """SynchronousDistributedTrainer on a pure-fsdp mesh (ZeRO-3-style)."""
    from distkeras_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"fsdp": 8})
    trainer = dk.SynchronousDistributedTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        batch_size=8, num_epoch=6, mesh=mesh,
    )
    trained = trainer.train(toy_classification)
    assert _accuracy(trained, toy_classification) > 0.85


def test_async_islands_sync_submesh(toy_classification):
    """2 async islands x 4-device sync sub-meshes (the SURVEY §7 hybrid)."""
    trainer = dk.ADAG(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        num_workers=2, devices_per_worker=4, batch_size=8, num_epoch=6,
        communication_window=3,
    )
    trained = trainer.train(toy_classification)
    assert _accuracy(trained, toy_classification) > 0.85
    assert trainer.parameter_server.num_commits > 0


def test_ensemble_predictor_averages(toy_classification):
    from distkeras_tpu.inference.predictors import EnsemblePredictor

    trainer = dk.EnsembleTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01, num_models=3,
        batch_size=16, num_epoch=4,
    )
    models = trainer.train(toy_classification)
    pred = EnsemblePredictor(models, batch_size=128)
    out = pred.predict(toy_classification)
    probs = out["prediction"]
    assert probs.shape == (len(toy_classification), 2)
    assert np.allclose(probs.sum(-1), 1.0, atol=1e-5)  # averaged softmax
    out = dk.LabelIndexTransformer(input_col="prediction").transform(out)
    acc = dk.AccuracyEvaluator(
        prediction_col="prediction_index", label_col="label"
    ).evaluate(out)
    assert acc > 0.85, acc


def test_single_trainer_deterministic(toy_classification):
    """Same seed, same data -> bit-identical weights (reproducibility)."""
    def run():
        t = dk.SingleTrainer(
            _model(), worker_optimizer="adam", learning_rate=0.01,
            batch_size=32, num_epoch=2, seed=11,
        )
        return t.train(toy_classification, shuffle=True)

    w1 = run().params["Dense_0"]["kernel"]
    w2 = run().params["Dense_0"]["kernel"]
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))


def test_grad_accumulation_matches_full_batch(toy_classification):
    """k-way accumulated gradients == one full-batch step (SGD, no noise)."""
    import optax
    from distkeras_tpu.training.step import TrainState, make_train_step

    model = _model()
    opt = optax.sgd(0.1)
    s0 = TrainState.create(model, opt, rng=0)
    batch = {
        "features": toy_classification["features"][:64],
        "label": toy_classification["label"][:64],
    }
    full = make_train_step(model, opt, "categorical_crossentropy", donate=False)
    accum = make_train_step(model, opt, "categorical_crossentropy", donate=False,
                            grad_accum_steps=4)
    s1, m1 = full(s0, batch)
    s2, m2 = accum(s0, batch)
    # bf16 matmuls: micro-batch partial sums differ from full-batch at ~1e-4
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-3)
    np.testing.assert_allclose(
        np.asarray(s1.params["Dense_0"]["kernel"]),
        np.asarray(s2.params["Dense_0"]["kernel"]),
        atol=1e-3,
    )


def test_optax_schedule_and_optimizer_passthrough(toy_classification):
    """An optax GradientTransformation (with an LR schedule) passes straight
    through worker_optimizer."""
    import optax

    schedule = optax.cosine_decay_schedule(0.02, decay_steps=100)
    trainer = dk.SingleTrainer(
        _model(), worker_optimizer=optax.adam(schedule),
        batch_size=32, num_epoch=6,
    )
    trained = trainer.train(toy_classification)
    assert _accuracy(trained, toy_classification) > 0.85


def test_single_trainer_accum_and_remat_flags(toy_classification):
    trainer = dk.SingleTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        batch_size=32, num_epoch=6, grad_accum_steps=2, remat=True,
    )
    trained = trainer.train(toy_classification)
    assert _accuracy(trained, toy_classification) > 0.85


def test_loss_weights_scales_loss(toy_classification):
    t1 = dk.SingleTrainer(_model(), worker_optimizer="sgd", learning_rate=0.0,
                          batch_size=32, num_epoch=1)
    t2 = dk.SingleTrainer(_model(), worker_optimizer="sgd", learning_rate=0.0,
                          batch_size=32, num_epoch=1, loss_weights=2.0)
    t1.train(toy_classification)
    t2.train(toy_classification)
    l1 = t1.get_history()[0]["loss"]
    l2 = t2.get_history()[0]["loss"]
    np.testing.assert_allclose(l2, 2 * l1, rtol=1e-5)


def test_sync_trainer_zero1(toy_classification):
    trainer = dk.SynchronousDistributedTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        batch_size=8, num_epoch=6, zero1=True,
    )
    trained = trainer.train(toy_classification)
    assert _accuracy(trained, toy_classification) > 0.85


def test_validation_history(toy_classification):
    train, val = toy_classification.split(0.8, seed=0)
    trainer = dk.SingleTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        batch_size=32, num_epoch=3, validation_data=val,
    )
    trainer.train(train, shuffle=True)
    vh = trainer.validation_history
    assert len(vh) == 3
    assert {"epoch", "val_loss", "val_accuracy"} <= set(vh[0])
    assert vh[-1]["val_accuracy"] > 0.85


def test_device_cache_matches_host_feed(toy_classification):
    """The HBM-resident cached feed (index-gather inside the scanned
    window) must produce the same training as the host DeviceFeed path."""

    def run(device_cache):
        t = dk.ADAG(
            _model(), worker_optimizer="sgd", learning_rate=0.05,
            num_workers=1, batch_size=32, num_epoch=2,
            communication_window=4, overlap_window=False,
            device_cache=device_cache, seed=3,
        )
        t.train(toy_classification)
        return [h["loss"] for h in t.get_history()]

    cached, fed = run(True), run(False)
    assert len(cached) == len(fed)
    np.testing.assert_allclose(cached, fed, rtol=1e-5, atol=1e-6)


def test_ensemble_pads_to_device_multiple(toy_classification):
    """num_models not divisible by device count still device-shards (pads
    the replica axis; padded replicas dropped from results and metrics)."""
    t = dk.EnsembleTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        num_models=3, batch_size=32, num_epoch=2,
    )
    models = t.train(toy_classification)
    assert len(models) == 3
    h = t.get_history()
    assert all(v.shape[0] == 3 for rec in h for v in rec.values())


def test_averaging_ignores_padded_replicas(toy_classification):
    """AveragingTrainer with a non-device-multiple worker count averages
    ONLY the requested replicas, not the padded throwaways."""
    t = dk.AveragingTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01,
        num_workers=3, batch_size=32, num_epoch=2,
    )
    trained = t.train(toy_classification)
    # Average must equal the mean of the 3 unstacked replica param sets.
    stacked = t._train_replicas(toy_classification, shuffle=False)
    manual = np.mean(np.asarray(stacked.params["Dense_0"]["kernel"])[:3], axis=0)
    # (re-running _train_replicas retrains; just check shapes + finiteness
    # of the returned average and that the padded stack is wider)
    import jax

    ndev = len(jax.devices())
    n_padded = -(-3 // ndev) * ndev  # 3 replicas padded up to a device multiple
    assert np.asarray(stacked.params["Dense_0"]["kernel"]).shape[0] == n_padded
    assert manual.shape == np.asarray(trained.params["Dense_0"]["kernel"]).shape
    assert np.isfinite(np.asarray(trained.params["Dense_0"]["kernel"])).all()


def test_ensemble_uneven_partitions_reports_drop_count(rng):
    """Uneven partitions: lock-step vmapped stepping stops at the shortest
    replica stream; the tail drop must be explicit (dropped_batches), never
    silent. 70 rows // 3 -> partitions of 23/23/24 (linspace bounds); batch
    8 -> 2/2/3 batches, so replica 2 drops exactly 1."""
    x = np.asarray(rng.normal(size=(70, 16)), np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    ds = dk.Dataset.from_arrays(features=x, label=y)
    trainer = dk.EnsembleTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01, num_models=3,
        batch_size=8, num_epoch=1,
    )
    models = trainer.train(ds)
    assert len(models) == 3
    assert len(trainer.history) == 2  # min over replicas
    assert trainer.dropped_batches == [0, 0, 1]


def test_ensemble_even_partitions_drop_free(toy_classification):
    trainer = dk.EnsembleTrainer(
        _model(), worker_optimizer="adam", learning_rate=0.01, num_models=4,
        batch_size=16, num_epoch=2,
    )
    trainer.train(toy_classification)  # 512 rows -> 4x128 -> 8 batches each
    assert trainer.dropped_batches == [0, 0, 0, 0]


def test_device_cache_budget_derived_from_memory_stats():
    """VERDICT r3 task 4: the "auto" partition budget comes from the
    device's HBM limit minus resident-state and headroom reserves; the
    256 MB constant is only the no-stats fallback."""
    t = dk.ADAG(_model(), num_workers=1)

    class FakeDev:
        id = 0
        def __init__(self, limit):
            self._limit = limit
        def memory_stats(self):
            return {"bytes_limit": self._limit}

    gib = 1024**3
    state_bytes = 1 * gib
    # 16 GiB chip: 16 - 3*1 (state + grads + donation) - 4 (25% headroom)
    # = 9 GiB.
    assert t._device_cache_budget(FakeDev(16 * gib), state_bytes) == 9 * gib
    # Busy/small limit: budget clamps at zero, never negative.
    assert t._device_cache_budget(FakeDev(2 * gib), state_bytes) == 0

    class NoStats:
        id = 1
        def memory_stats(self):
            raise NotImplementedError

    assert (
        t._device_cache_budget(NoStats(), state_bytes)
        == t._DEVICE_CACHE_LIMIT
    )
    assert t._device_cache_budget(None, 0) == t._DEVICE_CACHE_LIMIT
