"""The shipped sample CSV loads through the full preprocessing pipeline
(reference examples/data/atlas_higgs.csv analogue)."""

import os

import numpy as np

import distkeras_tpu as dk

CSV = os.path.join(os.path.dirname(__file__), "..", "examples", "data",
                   "higgs_sample.csv")


def test_sample_csv_pipeline():
    names = [f"f{i}" for i in range(28)]
    ds = dk.Dataset.from_csv(CSV, features=names, label="label")
    assert ds.num_rows == 600
    assert ds["features"].shape == (600, 28)
    ds = dk.MinMaxTransformer(input_col="features",
                              output_col="features_normalized").transform(ds)
    ds = dk.OneHotTransformer(2, input_col="label",
                              output_col="label_encoded").transform(ds)
    f = ds["features_normalized"]
    assert f.min() >= 0.0 and f.max() <= 1.0
    assert ds["label_encoded"].shape == (600, 2)


def test_sample_csv_trains():
    names = [f"f{i}" for i in range(28)]
    ds = dk.Dataset.from_csv(CSV, features=names, label="label")
    ds = dk.MinMaxTransformer(input_col="features",
                              output_col="features_normalized").transform(ds)
    from distkeras_tpu.models import higgs_mlp

    trainer = dk.SingleTrainer(
        higgs_mlp(), worker_optimizer="adam", learning_rate=0.01,
        features_col="features_normalized", label_col="label",
        batch_size=32, num_epoch=15,
    )
    trained = trainer.train(ds, shuffle=True)
    out = dk.ModelPredictor(trained, features_col="features_normalized").predict(ds)
    out = dk.LabelIndexTransformer(input_col="prediction").transform(out)
    acc = dk.AccuracyEvaluator(prediction_col="prediction_index",
                               label_col="label").evaluate(out)
    assert acc > 0.78, acc
