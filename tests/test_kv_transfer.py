"""KV block migration + disaggregated prefill/decode serving.

The invariants under test:

- the KVX1 serialization layer round-trips **bitwise** (codec-level,
  engine-level unsharded, and through a heads-resharding tp=2 import —
  the payload always carries full heads, so compatible meshes adopt
  losslessly), and corrupt/incompatible payloads are typed
  ``KVTransferError`` rejects;
- a **weight-provenance mismatch** is a typed reject before any device
  work (KV is a pure function of (weights, tokens));
- every transfer failure — unreachable peer, pool-dry receiver,
  provenance mismatch — falls back to **monolithic** prefill with zero
  client-visible errors and correct tokens;
- a disaggregated fleet (prefill + decode roles behind the router) is
  **token-identical** to ``generate()`` under the armed
  ``RecompileAuditor``, with compile-count==1 on BOTH roles;
- cross-replica prefix sharing: a hot prompt is prefilled once per
  FLEET (the second identical request is a trie hit on the prefill
  replica and a block adoption on the decode side);
- **drain-by-migration**: a rolling reload with ``migrate=True`` moves
  live streams off the draining replica mid-generation — every stream
  completes token-identically with zero client errors;
- the router-level handoff/fallback logic runs **jax-free** against
  EchoReplica fleets (the KVBLK frames and kv_export/kv_prefill verbs
  are emulated; the pull client is the real one).
"""

import asyncio
import json

import numpy as np
import pytest

from distkeras_tpu.serving import wire
from distkeras_tpu.serving.kv_transfer import (
    KVTransferError,
    deserialize_blocks,
    peek_header,
    serialize_blocks,
)
from distkeras_tpu.serving.prefix_cache import KVBlockPool

VOCAB = 64
SUP = dict(health_interval_s=0.2, base_delay_s=0.2)


@pytest.fixture(scope="module")
def lm():
    from distkeras_tpu.models.bert import gpt_tiny

    model = gpt_tiny(seq_len=64, vocab_size=VOCAB)
    return model, model.init(0)


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


def _engine(lm, **kw):
    from distkeras_tpu.serving import ServingEngine

    model, variables = lm
    kw.setdefault("slots", 2)
    kw.setdefault("paged", True)
    kw.setdefault("kv_pool_blocks", 64)
    kw.setdefault("kv_block_tokens", 4)
    return ServingEngine(model, variables, **kw)


def _ref(lm, prompt, n):
    from distkeras_tpu.inference.generate import generate

    model, variables = lm
    return generate(model, variables, np.asarray([prompt], np.int32),
                    n, greedy=True)[0].tolist()


async def _kv_op(fn, arg):
    event, result = fn(arg)
    await asyncio.wait_for(event.wait(), 30)
    return result


# -- codec units (jax-free) --------------------------------------------------
def test_kvx1_codec_bitwise_and_typed_rejects():
    tokens = list(range(8))
    leaves = [np.arange(2 * 4 * 3 * 2, dtype=np.float32).reshape(2, 4, 3, 2),
              np.arange(2 * 4 * 5, dtype=np.int32).reshape(2, 4, 5)]
    payload = serialize_blocks(tokens, leaves, block_tokens=4,
                               provenance={"version": 3, "digest": "ab"})
    header, out = deserialize_blocks(payload)
    assert header["tokens"] == tokens
    assert header["provenance"] == {"version": 3, "digest": "ab"}
    for a, b in zip(leaves, out):
        assert a.dtype == b.dtype and a.tobytes() == b.tobytes()
    # Re-serialization of the decoded form is byte-identical.
    assert serialize_blocks(header["tokens"], out,
                            block_tokens=header["block_tokens"],
                            provenance=header["provenance"]) == payload
    # Typed rejects: bad magic, truncated leaf, trailing junk, token
    # count not covering the blocks.
    with pytest.raises(KVTransferError):
        peek_header(b"NOPE" + payload[4:])
    with pytest.raises(KVTransferError):
        deserialize_blocks(payload[:-3])
    with pytest.raises(KVTransferError):
        deserialize_blocks(payload + b"x")
    with pytest.raises(KVTransferError):
        serialize_blocks(tokens[:-1], leaves, block_tokens=4)


def test_request_extras_ride_the_binary_wire():
    spec = {"prompt": [1, 2, 3], "max_new_tokens": 4,
            "kv_from": {"host": "h", "port": 9},
            "resume_tokens": [7, 8, 9]}
    out = wire.decode_request(wire.encode_request(spec))
    assert out["kv_from"] == {"host": "h", "port": 9}
    assert out["resume_tokens"] == [7, 8, 9]
    # Plain requests stay byte-identical to pre-extras frames (no
    # extras flag, no trailing blob) and decode without the keys.
    plain = wire.encode_request({"prompt": [1], "max_new_tokens": 2})
    dec = wire.decode_request(plain)
    assert "kv_from" not in dec and "resume_tokens" not in dec
    # The affinity hash still clamps to the prompt bytes with extras
    # appended.
    assert wire.affinity_prefix(
        wire.encode_request(spec), 16) == np.asarray(
            [1, 2, 3], "<i4").tobytes()


def test_kvblk_frames_ride_the_scanner():
    """KVBLK frames split correctly through FrameDecoder — both the
    struct fallback and (when built) the native fastwire scan, which is
    frame-type-agnostic by design."""
    blob = serialize_blocks(list(range(4)), [], block_tokens=4)
    data = (wire.encode_frame(wire.T_KVBLK, 7, blob)
            + wire.encode_json_frame(wire.T_CTRLR, 8, {"ok": 1}))
    # Pure-python scan (small buffer).
    frames = wire.FrameDecoder().feed(data)
    assert [(t, s) for t, s, _ in frames] == [(wire.T_KVBLK, 7),
                                              (wire.T_CTRLR, 8)]
    assert frames[0][2] == blob
    if wire.native_available():
        # Pad past the small-buffer crossover so the native scan runs.
        big = data * 400
        frames = wire.FrameDecoder().feed(big)
        assert len(frames) == 800
        assert frames[0][2] == blob


def test_adopt_foreign_pool_dry_and_partial():
    pool = KVBlockPool(4, 4)
    tokens = list(range(16))  # 4 complete blocks
    uploads, resident = pool.adopt_foreign(tokens, 4)
    assert len(uploads) == 4 and resident == 4
    # Re-adoption of the same chain uploads nothing (already resident).
    uploads, resident = pool.adopt_foreign(tokens, 4)
    assert uploads == [] and resident == 4
    # A DRY pool (every block privately held, nothing evictable) adopts
    # what fits — here nothing — and never raises or evicts slot blocks.
    dry = KVBlockPool(2, 4)
    held = dry.alloc(2)
    assert held is not None and dry.blocks_free == 0
    uploads, resident = dry.adopt_foreign(tokens, 4)
    assert uploads == [] and resident == 0
    # Partial adoption keeps the contiguous prefix.
    part = KVBlockPool(2, 4)
    uploads, resident = part.adopt_foreign(tokens, 4)
    assert len(uploads) == 2 and resident == 2


# -- engine-level transfer ---------------------------------------------------
def test_export_import_bitwise_roundtrip_and_identical_continuation(
        lm, rng):
    async def main():
        e1 = _engine(lm)
        e2 = _engine(lm)
        t1 = asyncio.create_task(e1.run())
        t2 = asyncio.create_task(e2.run())
        prompt = _prompt(rng, 13)
        ref = _ref(lm, prompt, 6)
        got = await (e1.submit(prompt, 6)).result()
        assert got == ref
        res = await _kv_op(e1.request_kv_export, prompt)
        assert "error" not in res and res["matched_tokens"] >= 12
        payload = res["payload"]
        header, leaves = deserialize_blocks(payload)
        imp = await _kv_op(e2.request_kv_import, payload)
        assert imp["adopted_blocks"] == header["n_blocks"]
        assert imp["matched_tokens"] == res["matched_tokens"]
        # The adopted prefix serves: token-identical continuation, and
        # the pool registers the hit.
        got2 = await (e2.submit(prompt, 6)).result()
        assert got2 == ref
        assert e2.kv_pool.hit_tokens >= imp["matched_tokens"]
        # Re-export from the importer is BITWISE the original payload's
        # leaves (same tokens, same rows' contents).
        res2 = await _kv_op(e2.request_kv_export, prompt)
        _, leaves2 = deserialize_blocks(res2["payload"])
        for a, b in zip(leaves, leaves2):
            assert a.tobytes() == b.tobytes()
        e1.shutdown()
        e2.shutdown()
        await asyncio.gather(t1, t2)

    asyncio.run(main())


def test_sharded_import_reshards_heads_and_roundtrips(lm, rng):
    """An unsharded export adopts into a tp=2 pool (full heads in the
    payload; kv_pytree_shardings replaces them on upload) and exports
    back bitwise-identical — the compatible-mesh reshard contract."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices for tp=2")
    from distkeras_tpu.parallel.mesh import serving_mesh

    async def main():
        e1 = _engine(lm)
        e2 = _engine(lm, mesh=serving_mesh({"tp": 2},
                                           devices=jax.devices()[:2]))
        t1 = asyncio.create_task(e1.run())
        t2 = asyncio.create_task(e2.run())
        prompt = _prompt(rng, 12)
        ref = _ref(lm, prompt, 5)
        assert await (e1.submit(prompt, 5)).result() == ref
        res = await _kv_op(e1.request_kv_export, prompt)
        _, leaves = deserialize_blocks(res["payload"])
        imp = await _kv_op(e2.request_kv_import, res["payload"])
        assert "error" not in imp and imp["adopted_blocks"] >= 1
        assert await (e2.submit(prompt, 5)).result() == ref
        res2 = await _kv_op(e2.request_kv_export, prompt)
        _, leaves2 = deserialize_blocks(res2["payload"])
        for a, b in zip(leaves, leaves2):
            assert a.tobytes() == b.tobytes()
        e1.shutdown()
        e2.shutdown()
        await asyncio.gather(t1, t2)

    asyncio.run(main())


def test_provenance_mismatch_is_a_typed_reject(lm, rng):
    async def main():
        e1 = _engine(lm)
        e2 = _engine(lm, weight_version={"version": 7, "digest": "beef"})
        t1 = asyncio.create_task(e1.run())
        t2 = asyncio.create_task(e2.run())
        prompt = _prompt(rng, 12)
        await (e1.submit(prompt, 4)).result()
        res = await _kv_op(e1.request_kv_export, prompt)
        imp = await _kv_op(e2.request_kv_import, res["payload"])
        err = imp.get("error")
        assert isinstance(err, KVTransferError)
        assert "provenance" in str(err)
        assert err.code == "kv_transfer"
        # Nothing was adopted: the pool is untouched.
        assert e2.kv_pool.blocks_used == 0
        # Geometry mismatch rejects typed too.
        bad = _engine(lm, kv_block_tokens=8)
        t3 = asyncio.create_task(bad.run())
        imp = await _kv_op(bad.request_kv_import, res["payload"])
        assert isinstance(imp.get("error"), KVTransferError)
        assert "geometry" in str(imp["error"])
        e1.shutdown(), e2.shutdown(), bad.shutdown()
        await asyncio.gather(t1, t2, t3)

    asyncio.run(main())


def test_dense_engine_rejects_kv_transfer_typed(lm):
    from distkeras_tpu.serving import ServingEngine

    model, variables = lm
    dense = ServingEngine(model, variables, slots=1)
    with pytest.raises(KVTransferError):
        dense.request_kv_export([1, 2, 3])
    with pytest.raises(KVTransferError):
        dense.request_kv_import(b"")


# -- fleet-level disaggregation ----------------------------------------------
def _roles_cluster(lm, roles, registry=None, auditors=None,
                   router_kwargs=None, **engine_kw):
    from distkeras_tpu.serving import LocalReplica, ServingCluster
    from distkeras_tpu.telemetry import RecompileAuditor

    def factory(i):
        def build():
            kw = dict(engine_kw)
            if auditors is not None:
                auditors[i] = RecompileAuditor()
                kw.update(auditor=auditors[i],
                          arm_auditor_after_warmup=True)
            return _engine(lm, **kw)

        return LocalReplica(build)

    kwargs = {"affinity_tokens": 4, "min_handoff_tokens": 4}
    kwargs.update(router_kwargs or {})
    return ServingCluster(factory, len(roles), roles=roles,
                          registry=registry, supervisor_kwargs=SUP,
                          router_kwargs=kwargs)


def test_disaggregated_fleet_token_identical_armed_auditor(lm, rng):
    """The acceptance case: 1 prefill + 2 decode replicas behind the
    router, armed auditors everywhere — greedy output token-identical
    to generate(), every request migrated (zero fallbacks), and
    compile-count==1 on BOTH roles."""
    from distkeras_tpu.serving import ServingClient
    from distkeras_tpu.telemetry import MetricsRegistry

    async def main():
        registry = MetricsRegistry()
        auditors = {}
        cluster = _roles_cluster(lm, ["prefill", "decode", "decode"],
                                 registry=registry, auditors=auditors)
        prompts = [_prompt(rng, 12) for _ in range(5)]
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port,
                                     wire_mode="auto") as c:
                assert c.proto == wire.PROTO_BIN1
                for p in prompts:
                    done = await c.generate(p, 6)
                    assert done["tokens"] == _ref(lm, p, 6)
                    km = done.get("kv_migration")
                    assert km and "fallback" not in km, km
                    assert km["matched_tokens"] >= 12
            snap = registry.snapshot()
            assert snap["router_kv_handoffs_total"]["value"] == len(
                prompts)
            assert snap["router_kv_handoff_fallbacks_total"][
                "value"] == 0
            # Compile-count==1 on both roles, armed auditors silent.
            for rid, info in cluster.replicas.items():
                assert info.handle.engine.decode_compile_count() in (
                    0, 1), rid  # 0 = a decode replica that never ticked
            prefill_engine = cluster.replicas["r0"].handle.engine
            assert prefill_engine.metrics.kv_exports == len(prompts)
            decode_migrations = sum(
                cluster.replicas[r].handle.engine.metrics.kv_migrations
                for r in ("r1", "r2"))
            assert decode_migrations == len(prompts)
            # Fleet healthz rolls roles + migration sums up.
            async with ServingClient("127.0.0.1", cluster.port) as c:
                h = await c.healthz()
            assert h["router"]["roles"] == {"prefill": 1, "decode": 2}
            assert h["router"]["kv_migrations"]["migrations"] == len(
                prompts)
            for rid in ("r1", "r2"):
                assert h["replicas"][rid]["role"] == "decode"

    asyncio.run(main())


def test_prefix_share_prefills_once_per_fleet(lm, rng):
    """The same prompt through the fleet twice: the SECOND kv_prefill is
    a trie hit on the prefill replica (no recompute), whichever decode
    replica serves it."""
    from distkeras_tpu.serving import ServingClient

    async def main():
        cluster = _roles_cluster(lm, ["prefill", "decode", "decode"])
        prompt = _prompt(rng, 16)
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port) as c:
                ref = _ref(lm, prompt, 4)
                for _ in range(2):
                    done = await c.generate(prompt, 4)
                    assert done["tokens"] == ref
                    assert "fallback" not in (done.get("kv_migration")
                                              or {"fallback": 1})
            pe = cluster.replicas["r0"].handle.engine
            # Second kv_prefill matched the adopted chain: fleet-level
            # "prefilled once" — the pool saw a hit covering the
            # prompt's complete blocks.
            assert pe.kv_pool.hit_requests >= 1
            assert pe.kv_pool.hit_tokens >= 12

    asyncio.run(main())


def test_transfer_failure_falls_back_with_zero_client_errors(lm, rng):
    """Fault injection: the decode replica's pull target is unreachable
    (the router handed off, then the prefill replica vanished). The
    request must complete token-identically with a recorded fallback —
    never a client-visible error."""
    from distkeras_tpu.serving import ServingClient, ServingServer

    async def main():
        engine = _engine(lm)
        server = ServingServer(engine, port=0, kv_transfer_timeout_s=2.0)
        await server.start()
        prompt = _prompt(rng, 12)
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.port)
            # A spec pointing at a dead peer port: the pull fails, the
            # fallback prefills monolithic.
            spec = {"prompt": prompt, "max_new_tokens": 5,
                    "kv_from": {"host": "127.0.0.1", "port": 1}}
            writer.write((json.dumps(spec) + "\n").encode())
            await writer.drain()
            toks, done = [], None
            while done is None:
                rec = json.loads(await reader.readline())
                assert "error" not in rec, rec
                if "token" in rec:
                    toks.append(rec["token"])
                elif rec.get("done"):
                    done = rec
            assert done["tokens"] == _ref(lm, prompt, 5)
            assert "fallback" in done["kv_migration"]
            assert engine.metrics.kv_migration_fallbacks == 1
            assert engine.metrics.kv_migrations == 0
            writer.close()
        finally:
            await server.stop()

    asyncio.run(main())


def test_pool_dry_import_adopts_nothing_and_reports(lm, rng):
    """A receiver whose pool is fully privately held adopts zero blocks
    — the server maps that to a fallback, never an error."""
    async def main():
        e1 = _engine(lm)
        t1 = asyncio.create_task(e1.run())
        prompt = _prompt(rng, 12)
        await (e1.submit(prompt, 4)).result()
        res = await _kv_op(e1.request_kv_export, prompt)
        e2 = _engine(lm, kv_pool_blocks=4)
        held = e2.kv_pool.alloc(4)  # every block privately held
        assert held is not None
        imp = e2._kv_import_sync(res["payload"])
        assert imp["adopted_blocks"] == 0
        assert imp["resident_blocks"] == 0
        e1.shutdown()
        await t1

    asyncio.run(main())


def test_drain_via_migration_rolling_reload_under_load(lm, rng,
                                                       tmp_path):
    """Live slot migration: a rolling reload with migrate=True moves
    in-flight streams off each draining replica instead of waiting
    them out — every stream completes token-identically (the reload
    re-installs the SAME weight bytes) with zero client errors, and
    the roll reports the migrations."""
    from distkeras_tpu.checkpoint import save_weights_file
    from distkeras_tpu.serving import ServingClient
    from distkeras_tpu.telemetry import MetricsRegistry

    model, variables = lm
    path = str(tmp_path / "weights.npz")
    save_weights_file(path, variables)

    async def main():
        registry = MetricsRegistry()
        cluster = _roles_cluster(lm, ["monolithic", "monolithic"],
                                 registry=registry)
        prompts = [_prompt(rng, 8) for _ in range(4)]
        refs = [_ref(lm, p, 40) for p in prompts]
        async with cluster:
            async def one(p, ref):
                async with ServingClient("127.0.0.1",
                                         cluster.port) as c:
                    done = await c.generate(p, 40)
                    assert done["tokens"] == ref, "migrated stream "
                    "diverged"

            tasks = [asyncio.create_task(one(p, r))
                     for p, r in zip(prompts, refs)]
            # Let the streams get into flight, then roll with
            # migration.
            await asyncio.sleep(0.4)
            async with ServingClient("127.0.0.1", cluster.port) as c:
                rep = await c.reload(path, timeout=120.0, migrate=True)
            assert rep["ok"], rep
            await asyncio.gather(*tasks)
            migrated = rep.get("migrated_streams", 0)
            snap = registry.snapshot()
            assert migrated >= 1, (rep, snap)
            assert snap["router_stream_migrations_total"][
                "value"] >= 1
            assert snap["router_streams_lost_total"]["value"] == 0

    asyncio.run(main())


# -- jax-free router handoff (Echo fleet) ------------------------------------
def test_echo_fleet_handoff_and_fallback_jax_free():
    """Router handoff logic against an engine-free Echo fleet: the
    happy path runs the REAL KVBLK pull (fetch_blocks against the
    emulated kv_export), and a kv_fail prefill replica exercises the
    fallback path — generation never fails either way."""
    from distkeras_tpu.serving import ServingClient, ServingCluster
    from distkeras_tpu.serving.cluster.replicas import EchoReplica
    from distkeras_tpu.telemetry import MetricsRegistry

    async def run_fleet(kv_fail):
        registry = MetricsRegistry()
        cluster = ServingCluster(
            lambda i: EchoReplica(kv_fail=kv_fail, kv_block_tokens=4),
            3, roles=["prefill", "decode", "decode"], registry=registry,
            supervisor_kwargs=SUP,
            router_kwargs={"affinity_tokens": 4,
                           "min_handoff_tokens": 4})
        async with cluster:
            async with ServingClient("127.0.0.1", cluster.port,
                                     wire_mode="auto") as c:
                done = await c.generate([5, 6, 7, 8, 9], 1)
                assert done["tokens"] == [5]
                km = done.get("kv_migration")
            prefill = cluster.replicas["r0"].handle.server
            snap = registry.snapshot()
            return km, prefill, snap

    async def main():
        km, prefill, snap = await run_fleet(kv_fail=False)
        assert km and "fallback" not in km, km
        assert km["matched_tokens"] == 4  # one 4-token block
        assert prefill.kv_prefills == 1 and prefill.kv_exports == 1
        assert snap["router_kv_handoffs_total"]["value"] == 1
        km, prefill, snap = await run_fleet(kv_fail=True)
        assert km is None  # no handoff arranged -> no kv_from
        assert snap["router_kv_handoff_fallbacks_total"]["value"] == 1
        assert snap["router_kv_handoffs_total"]["value"] == 0

    asyncio.run(main())


# -- multi-frame chunking + peer connection pool (jax-free) ------------------
def test_split_frames_join_bitwise_roundtrip():
    """An oversize payload splits into sequenced KVXC chunk frames with
    a terminal marker and reassembles BITWISE; a payload that fits one
    frame stays byte-identical to the pre-chunking wire (old receivers
    keep working)."""
    from distkeras_tpu.serving.kv_transfer import (
        FrameJoiner,
        is_chunk_frame,
        split_frames,
    )

    small = b"KVX1" + bytes(range(256)) * 10
    assert split_frames(small) == [small]
    assert not is_chunk_frame(small)

    rng = np.random.default_rng(3)
    big = bytes(rng.integers(0, 256, size=5000, dtype=np.uint8))
    frames = split_frames(big, max_frame_bytes=1024)
    assert len(frames) > 1
    assert all(is_chunk_frame(f) for f in frames)
    assert all(len(f) <= 1024 for f in frames)
    joiner = FrameJoiner()
    out = None
    for i, f in enumerate(frames):
        whole = joiner.feed(f)
        if i < len(frames) - 1:
            assert whole is None  # terminal marker not yet seen
        else:
            out = whole
    assert out == big  # bitwise


def test_frame_joiner_typed_rejects():
    """Out-of-order / duplicate / disagreeing-total / over-cap chunk
    sequences are typed KVTransferError rejects, never a hang or an
    unbounded buffer."""
    from distkeras_tpu.serving.kv_transfer import (
        FrameJoiner,
        split_frames,
    )

    big = bytes(range(256)) * 20
    frames = split_frames(big, max_frame_bytes=512)
    assert len(frames) >= 3
    # out of order
    j = FrameJoiner()
    j.feed(frames[0])
    with pytest.raises(KVTransferError, match="out of order"):
        j.feed(frames[2])
    # duplicate (same seq twice)
    j = FrameJoiner()
    j.feed(frames[0])
    with pytest.raises(KVTransferError, match="out of order"):
        j.feed(frames[0])
    # bare payload mid-sequence
    j = FrameJoiner()
    j.feed(frames[0])
    with pytest.raises(KVTransferError, match="mid chunk"):
        j.feed(b"KVX1whatever")
    # total cap enforced during reassembly
    j = FrameJoiner(max_total_bytes=600)
    with pytest.raises(KVTransferError, match="cap"):
        for f in frames:
            j.feed(f)
    # oversize refusal at the split site
    from distkeras_tpu.serving import kv_transfer as kvt

    with pytest.raises(KVTransferError, match="cap"):
        split_frames(b"x" * (kvt.MAX_TOTAL_TRANSFER_BYTES + 1))


def test_fetch_blocks_pools_peer_connections():
    """The decode-side pull path reuses ONE negotiated connection per
    peer across migrations (the router's pooled-conn pattern): N pulls
    = 1 dial, and a peer restart (dead pooled socket) costs one
    transparent re-dial, never a fallback."""
    from distkeras_tpu.serving.cluster.replicas import EchoServer
    from distkeras_tpu.serving.kv_transfer import (
        PeerConnectionPool,
        fetch_blocks,
    )

    async def main():
        server = EchoServer(kv_block_tokens=4)
        await server.start()
        pool = PeerConnectionPool()
        try:
            for _ in range(4):
                payload = await fetch_blocks(
                    "127.0.0.1", server.port, [1, 2, 3, 4, 5],
                    timeout=5, pool=pool)
                assert payload is not None
                header = peek_header(payload)
                assert header["block_tokens"] == 4
            assert pool.dials == 1, pool.stats()
            assert pool.reuses == 3, pool.stats()

            # A restarted peer presents a dead pooled socket (the old
            # incarnation's connections die with its process): simulate
            # by closing the idle transport; the checkout probe must
            # discard it and re-dial transparently — never a fallback.
            for conns in pool._idle.values():
                for _r, w in conns:
                    w.close()
            await asyncio.sleep(0)  # let the transport close
            payload = await fetch_blocks(
                "127.0.0.1", server.port, [1, 2, 3, 4, 5],
                timeout=5, pool=pool)
            assert payload is not None
            assert pool.dials == 2, pool.stats()
        finally:
            await server.stop()
            pool.close_all()

    asyncio.run(main())


def test_chunked_export_reassembles_over_the_wire(lm, rng):
    """End-to-end multi-frame transfer against a REAL jax server: the
    export side splits via split_frames, fetch_blocks reassembles, and
    the re-imported chain round-trips bitwise — proven by forcing the
    per-frame bound below one block's bytes so every export chunks."""
    from distkeras_tpu.serving import kv_transfer as kvt
    from distkeras_tpu.serving.server import ServingServer

    prompt = _prompt(rng, 16)

    async def main():
        engine = _engine(lm)
        server = ServingServer(engine, port=0)
        await server.start()  # owns the engine.run() task
        try:
            req = engine.submit(prompt, 1)
            await req.result()
            # Direct export for the reference payload.
            ref = await _kv_op(engine.request_kv_export, prompt)
            assert ref.get("payload"), ref
            # Force chunking: every frame far smaller than the payload.
            orig = kvt.MAX_TRANSFER_BYTES
            kvt.MAX_TRANSFER_BYTES = 1024
            try:
                pulled = await fetch_blocks_patched(
                    "127.0.0.1", server.port, prompt)
            finally:
                kvt.MAX_TRANSFER_BYTES = orig
            assert pulled == ref["payload"]  # bitwise through the wire
        finally:
            await server.stop()

    async def fetch_blocks_patched(host, port, tokens):
        from distkeras_tpu.serving.kv_transfer import (
            PeerConnectionPool,
            fetch_blocks,
        )

        pool = PeerConnectionPool()
        try:
            return await fetch_blocks(host, port, tokens, timeout=10,
                                      pool=pool)
        finally:
            pool.close_all()

    asyncio.run(main())
