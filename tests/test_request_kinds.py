"""Typed request kinds: forked sampling, scoring/embedding, constrained
decoding — one subsystem threaded client -> wire -> router -> scheduler
-> engine.

The invariants under test, all on CPU with a tiny causal LM (the
router/QoS/wire layers run jax-free on EchoServer):

- **fork parity**: ``kind="sample"`` with ``n`` forks is token-identical
  to ``n`` sequential greedy generates — ONE prefill, ``n`` decode rows
  sharing the prompt's KV blocks copy-on-write;
- **CoW accounting is exact**: fork refcounts drain to zero and the pool
  returns to full capacity after a flush — no leaked or double-freed
  block, with ``kv_fork_blocks_total`` counting the shared rows;
- **scoring** returns per-token logprobs matching a hand-rolled dense
  forward pass + log_softmax; **embedding** returns the mean-pooled
  final hidden state — both prefill-only (no decode slot occupied);
- **constrained decoding** obeys the token automaton on EVERY emitted
  token, greedy and under speculative verify (forbidden drafts are
  rejected before they can commit), with the mask uploaded under the
  dirty-flag pattern so the ARMED ``RecompileAuditor`` proves the
  decode step still compiled exactly once across a mixed batch of all
  kinds;
- **admission-typed validation**: contradictory combos (score with
  max_new_tokens>0, n>1 outside sample, constraint on an unconstrained
  engine) reject as ``bad_request`` at submit, never mid-stream;
- **QoS**: scorelike traffic is its own ``tenant#score`` class — a
  flooding scoring tenant sheds TYPED while the same tenant's
  interactive decode is untouched;
- the whole contract survives real TCP on BOTH protocols (JSONL and
  bin1 extras), and EchoServer emulates it so router tests stay
  jax-free.
"""

import asyncio
import json

import numpy as np
import pytest

from distkeras_tpu.serving import wire
from distkeras_tpu.serving.scheduler import Request, Scheduler, TenantOverQuota

VOCAB = 64


# -- wire: kind extras ride the bin1 whitelist (jax-free) --------------------

def test_wire_roundtrip_kind_extras():
    spec = {"prompt": [1, 2, 3], "max_new_tokens": 0,
            "temperature": 0.0, "priority": 0, "timeout": None,
            "speculate": False, "kind": "score"}
    assert wire.decode_request(wire.encode_request(spec)) == spec
    spec2 = {"prompt": [5, 6], "max_new_tokens": 4, "temperature": 0.5,
             "priority": 0, "timeout": None, "speculate": False,
             "kind": "sample", "n": 3}
    assert wire.decode_request(wire.encode_request(spec2)) == spec2
    con = {"start": 0, "edges": [[0, 1, 0]]}
    spec3 = {"prompt": [5], "max_new_tokens": 2, "temperature": 0.0,
             "priority": 0, "timeout": None, "speculate": True,
             "constraint": con}
    assert wire.decode_request(wire.encode_request(spec3)) == spec3


def test_request_flags_distinguishes_extras_payloads():
    """The router's fast path peeks the flags byte to bounce
    extras-bearing REQs (kinds, kv hints) onto the kind-aware classic
    dispatch — a plain generate must NOT carry the extras flag."""
    plain = wire.encode_request(
        {"prompt": [1, 2], "max_new_tokens": 4, "temperature": 0.0,
         "priority": 0, "timeout": None, "speculate": True})
    kinded = wire.encode_request(
        {"prompt": [1, 2], "max_new_tokens": 0, "temperature": 0.0,
         "priority": 0, "timeout": None, "speculate": False,
         "kind": "embed"})
    assert not wire.request_flags(plain) & wire._F_EXTRAS
    assert wire.request_flags(kinded) & wire._F_EXTRAS
    assert wire.request_flags(b"") == 0  # malformed: typed later, not here


# -- scheduler: scorelike QoS class (jax-free) -------------------------------

def test_scorelike_requests_form_their_own_qos_class():
    r = Request(list(range(8)), 0, kind="score", tenant="acme")
    assert r.qos_tenant == "acme#score"
    g = Request([1, 2], 4, tenant="acme")
    assert g.qos_tenant == "acme"
    # Scorelike quota charge is prompt-shaped, generate charge is
    # decode-shaped.
    assert r.consumed_tokens() == 8
    assert g.consumed_tokens() == 0  # nothing generated yet


def test_flooding_scoring_tenant_sheds_typed_decode_unaffected():
    """A scoring flood from tenant ``bulk`` hits the ``bulk#score``
    quota and rejects TYPED at submit; the SAME tenant's interactive
    generates (different QoS class) sail through untouched."""
    async def go():
        s = Scheduler(max_depth=64, tenant_quotas={"bulk#score": 16.0},
                      quota_burst_s=1.0)  # capacity: 16 prompt tokens
        first = Request(list(range(12)), 0, kind="score", tenant="bulk")
        s.submit(first)
        assert first.qos_tenant == "bulk#score"
        with pytest.raises(TenantOverQuota):
            s.submit(Request(list(range(12)), 0, kind="score",
                             tenant="bulk"))
        # Interactive decode from the same tenant: unmetered class.
        for _ in range(4):
            s.submit(Request([1, 2, 3], 8, tenant="bulk"))
        stats = s.tenant_stats()
        assert stats["bulk#score"]["over_quota_rejects"] == 1
        assert "over_quota_rejects" not in stats.get("bulk", {}) or \
            stats["bulk"]["over_quota_rejects"] == 0

    asyncio.run(go())


# -- router: scoring steers at prefill-shaped replicas (jax-free) ------------

def test_router_pick_routes_scoring_to_prefill_shaped():
    import types

    from distkeras_tpu.serving.cluster.replicas import READY, ReplicaInfo
    from distkeras_tpu.serving.cluster.router import Router

    def info(rid, role, outstanding=0):
        r = ReplicaInfo(rid=rid, index=0, handle=None, status=READY,
                        role=role)
        r.outstanding = outstanding
        return r

    sup = types.SimpleNamespace(
        replicas={
            "p0": info("p0", "prefill", 1),
            "d0": info("d0", "decode", 0),
            "m0": info("m0", "monolithic", 3),
        },
        on_replica_death=[])
    router = Router(sup, trace_capacity=0)
    # Generation: prefill replicas never take dispatches.
    pick = router._pick([1, 2, 3], set())
    assert pick.role != "prefill"
    # Scoring: prefill-shaped work prefers prefill/monolithic rows
    # (least-outstanding among them), keeping decode slots for streams.
    pick = router._pick([1, 2, 3], set(), kind="score")
    assert pick.rid == "p0"
    pick = router._pick([1, 2, 3], set(), kind="embed")
    assert pick.rid == "p0"
    # ... but falls back to ANY ready replica rather than failing.
    sup.replicas = {"d0": info("d0", "decode", 0)}
    assert router._pick([1], set(), kind="score").rid == "d0"


# -- EchoServer emulates the kinds (jax-free; satellite 1) -------------------

async def _echo_jsonl(server, spec):
    reader, writer = await asyncio.open_connection("127.0.0.1",
                                                   server.port)
    writer.write((json.dumps(spec) + "\n").encode())
    await writer.drain()
    recs = []
    while True:
        rec = json.loads(await reader.readline())
        recs.append(rec)
        if "token" not in rec:  # done / error / control reply
            break
    writer.close()
    return recs


def test_echo_server_emulates_kinds_jsonl_and_bin1():
    from distkeras_tpu.serving.client import ServingClient
    from distkeras_tpu.serving.cluster.replicas import EchoServer

    async def go():
        server = EchoServer(echo_tokens=3)
        await server.start()
        # JSONL shapes.
        recs = await _echo_jsonl(server, {
            "prompt": [7, 8], "max_new_tokens": 5, "kind": "sample",
            "n": 2})
        done = recs[-1]
        assert done["kind"] == "sample"
        assert done["completions"] == [[7, 7, 7], [7, 7, 7]]
        assert done["tokens"] == []
        recs = await _echo_jsonl(server, {
            "prompt": [7, 8, 9], "max_new_tokens": 0, "kind": "score"})
        assert recs[-1]["logprobs"] == [0.0, 0.0]
        recs = await _echo_jsonl(server, {
            "prompt": [7], "max_new_tokens": 0, "kind": "embed"})
        assert len(recs[-1]["embedding"]) == 4
        # Contradictory combos reject typed (satellite 2's contract,
        # mirrored so router tests exercise it jax-free).
        recs = await _echo_jsonl(server, {
            "prompt": [7], "max_new_tokens": 3, "kind": "score"})
        assert recs[-1]["code"] == "bad_request"
        recs = await _echo_jsonl(server, {
            "prompt": [7], "max_new_tokens": 3, "n": 4})
        assert recs[-1]["code"] == "bad_request"
        # bin1: the same shapes ride the extras whitelist.
        async with ServingClient("127.0.0.1", server.port,
                                 wire_mode="bin1") as c:
            done = await c.sample([7, 8], 5, 2)
            assert done["completions"] == [[7, 7, 7], [7, 7, 7]]
            done = await c.score([7, 8, 9])
            assert done["logprobs"] == [0.0, 0.0]
            done = await c.embed([7])
            assert len(done["embedding"]) == 4

            # A contradictory combo rejects typed over bin1 too.
            async def bad():
                async for _ in c.stream([7], 3, kind="score"):
                    pass
            with pytest.raises(Exception):
                await bad()
        assert server.kind_requests["sample"] == 2
        assert server.kind_requests["score"] == 2
        assert server.kind_requests["embed"] == 2
        mz = (await _echo_jsonl(server,
                                {"cmd": "metricsz"}))[0]["metricsz"]
        assert mz['serving_requests_total{kind="sample"}']["value"] == 2
        await server.stop()

    asyncio.run(go())


# -- engine: the three kinds end to end --------------------------------------

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from distkeras_tpu.inference.generate import (  # noqa: E402
    _decode_module,
    _empty_cache,
    generate,
)
from distkeras_tpu.models.bert import gpt_tiny  # noqa: E402
from distkeras_tpu.serving import ServingEngine  # noqa: E402


@pytest.fixture(scope="module")
def lm():
    model = gpt_tiny(seq_len=32, vocab_size=VOCAB)
    return model, model.init(0)


@pytest.fixture
def rng():
    return np.random.default_rng(13)


def _prompt(rng, n):
    return rng.integers(0, VOCAB, size=(n,)).tolist()


def _want(lm, prompt, n):
    model, variables = lm
    return generate(model, variables, np.asarray([prompt], np.int32), n,
                    greedy=True)[0].tolist()


async def _run_engine(engine, coro):
    task = asyncio.create_task(engine.run())
    try:
        return await coro
    finally:
        engine.shutdown(drain=True)
        await task


def test_submit_validation_rejects_contradictions_typed(lm):
    """Satellite 2: every contradictory combo is a typed reject AT
    admission — the stream never starts."""
    model, variables = lm
    eng = ServingEngine(model, variables, slots=2, kv_pool_blocks=32,
                        kv_block_tokens=4)
    with pytest.raises(ValueError):
        eng.submit([1, 2], 4, kind="score")  # score decodes nothing
    with pytest.raises(ValueError):
        eng.submit([1, 2], 0, kind="embed", n=2)  # n outside sample
    with pytest.raises(ValueError):
        eng.submit([1, 2], 4, n=3)  # n requires kind="sample"
    with pytest.raises(ValueError):
        eng.submit([1, 2], 4, kind="sample", n=1)  # fork of one
    with pytest.raises(ValueError):
        eng.submit([1, 2], 4, kind="sample", n=99)  # n > slots
    with pytest.raises(ValueError):
        eng.submit([1, 2], 4, kind="nope")
    with pytest.raises(ValueError):
        # Mask hook not compiled in: reject up front, not mid-stream.
        eng.submit([1, 2], 4,
                   constraint={"start": 0, "edges": [[0, 1, 0]]})
    dense = ServingEngine(model, variables, slots=2)
    with pytest.raises(ValueError):
        dense.submit([1, 2], 0, kind="score")  # kinds need paging


def test_fork_parity_and_exact_cow_accounting(lm, rng):
    """Tentpole (a): one prefill, n CoW forks — token-identical to n
    sequential generates, and the pool's fork refcounts drain exactly
    (flush returns EVERY block; no leak, no double-free)."""
    model, variables = lm
    eng = ServingEngine(model, variables, slots=4, max_queue=16,
                        kv_pool_blocks=64, kv_block_tokens=4)
    p = _prompt(rng, 9)  # 2 complete blocks + a partial tail
    want = _want(lm, p, 6)

    async def work():
        req = eng.submit(p, 6, kind="sample", n=3, speculate=False)
        await req.result()
        return req

    req = asyncio.run(_run_engine(eng, work()))
    assert req.fork_completions == [want, want, want]
    pool = eng.kv_pool
    assert pool.forked_blocks_total > 0
    assert eng.metrics.fork_blocks > 0
    assert pool._fork_refs == {}  # every shared ref consumed
    used_before_flush = pool.blocks_used
    pool.flush()
    assert pool.blocks_free == pool.capacity, (
        f"leaked {pool.capacity - pool.blocks_free} blocks "
        f"(used pre-flush: {used_before_flush})")


def test_score_logprobs_match_dense_forward(lm, rng):
    """Tentpole (b): engine scoring == hand-rolled forward pass +
    log_softmax, chunked prefill and paging notwithstanding."""
    model, variables = lm
    module, _ = _decode_module(model)
    eng = ServingEngine(model, variables, slots=2, max_queue=8,
                        kv_pool_blocks=64, kv_block_tokens=4)
    p = _prompt(rng, 11)

    logits, _ = module.apply(
        {"params": variables["params"], "cache": _empty_cache(module, 1)},
        jnp.asarray([p], jnp.int32), train=False, mutable=["cache"])
    logp = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
    want = [float(logp[i, p[i + 1]]) for i in range(len(p) - 1)]

    async def work():
        req = eng.submit(p, 0, kind="score")
        toks = await req.result()
        return toks, req

    toks, req = asyncio.run(_run_engine(eng, work()))
    assert toks == []  # nothing decoded
    # bf16 trunk: paged vs dense attention reorder roundings at the
    # 2^-9 ULP scale; a positional bug would be off by whole units.
    np.testing.assert_allclose(req.logprobs, want, atol=2e-2)
    assert req.ttft > 0  # prefill completion stamped first-token time


def test_embed_matches_mean_pooled_hidden(lm, rng):
    model, variables = lm
    module, _ = _decode_module(model)
    eng = ServingEngine(model, variables, slots=2, max_queue=8,
                        kv_pool_blocks=64, kv_block_tokens=4)
    p = _prompt(rng, 7)

    hidden, _ = module.apply(
        {"params": variables["params"], "cache": _empty_cache(module, 1)},
        jnp.asarray([p], jnp.int32), train=False, mutable=["cache"],
        return_hidden=True)
    want = np.asarray(hidden[0], np.float64).mean(axis=0)

    async def work():
        req = eng.submit(p, 0, kind="embed")
        await req.result()
        return req

    req = asyncio.run(_run_engine(eng, work()))
    # Same bf16-ULP tolerance story as the scoring parity test.
    np.testing.assert_allclose(req.embedding, want, rtol=5e-2, atol=5e-2)


def _alternating_dfa():
    """Tokens must alternate 1, 2, 1, 2, ... forever (no terminal)."""
    return {"start": 0, "edges": [[0, 1, 1], [1, 2, 0]]}


def test_masked_greedy_obeys_automaton_every_token(lm, rng):
    """Tentpole (c): the per-slot mask forces every emitted token onto a
    DFA edge; a terminal state ends the stream early."""
    model, variables = lm
    eng = ServingEngine(model, variables, slots=2, max_queue=8,
                        kv_pool_blocks=64, kv_block_tokens=4,
                        constrained=True)
    p = _prompt(rng, 6)
    # 3, then 4, then STOP (state 2 has no outgoing edges).
    terminal = {"start": 0, "edges": [[0, 3, 1], [1, 4, 2]]}

    async def work():
        alt = eng.submit(p, 6, constraint=_alternating_dfa())
        fin = eng.submit(p, 6, constraint=terminal)
        plain = eng.submit(p, 6)  # unconstrained neighbor, same batch
        return (await alt.result(), await fin.result(),
                await plain.result())

    alt, fin, plain = asyncio.run(_run_engine(eng, work()))
    assert alt == [1, 2, 1, 2, 1, 2]
    assert fin == [3, 4]  # terminal state stopped the stream early
    assert plain == _want(lm, p, 6)  # the mask never leaks across slots


def test_speculative_verify_under_masks_parity(lm, rng):
    """Forbidden draft tokens are rejected BEFORE they can commit: a
    constrained stream on a speculative engine emits the same tokens as
    on a plain constrained engine, while unconstrained neighbors still
    speculate."""
    model, variables = lm
    spec = ServingEngine(model, variables, slots=2, max_queue=8,
                         kv_pool_blocks=64, kv_block_tokens=4,
                         draft_model=model, draft_variables=variables,
                         spec_k=4, constrained=True)
    p = _prompt(rng, 6)

    async def work(engine):
        con = engine.submit(p, 6, constraint=_alternating_dfa())
        plain = engine.submit(p, 6)
        return await con.result(), await plain.result()

    con, plain = asyncio.run(_run_engine(spec, work(spec)))
    assert con == [1, 2, 1, 2, 1, 2]
    assert plain == _want(lm, p, 6)
    assert spec.metrics.spec_draft_tokens > 0


def test_mixed_batch_armed_auditor_compile_once(lm, rng):
    """THE compile invariant survives the kinds: one decode executable
    serves generate + sample forks + constrained rows in one mixed
    batch, while score/embed ride the prefill path — under an ARMED
    auditor, ``serving_decode`` compiled exactly once."""
    from distkeras_tpu.telemetry import RecompileAuditor

    model, variables = lm
    auditor = RecompileAuditor()
    eng = ServingEngine(model, variables, slots=4, max_queue=16,
                        kv_pool_blocks=64, kv_block_tokens=4,
                        constrained=True, auditor=auditor,
                        arm_auditor_after_warmup=True)
    prompts = [_prompt(rng, n) for n in (5, 7, 6, 4, 8)]

    async def work():
        gen = eng.submit(prompts[0], 5)
        await asyncio.sleep(0.02)  # decode starts; auditor arms
        fork = eng.submit(prompts[1], 4, kind="sample", n=2,
                          speculate=False)
        con = eng.submit(prompts[2], 4,
                         constraint=_alternating_dfa())
        score = eng.submit(prompts[3], 0, kind="score")
        embed = eng.submit(prompts[4], 0, kind="embed")
        return [await r.result()
                for r in (gen, fork, con, score, embed)]

    out = asyncio.run(_run_engine(eng, work()))
    assert out[0] == _want(lm, prompts[0], 5)
    assert out[2] == [1, 2, 1, 2]
    assert auditor.compiles("serving_decode") == 1
    kinds = eng.metrics.kind_counters()
    assert kinds["generate"] >= 2  # plain + constrained
    assert kinds["sample"] == 1 and kinds["score"] == 1
    assert kinds["embed"] == 1
    dz = eng.debugz()
    assert dz["request_kinds"] == kinds


def test_tcp_end_to_end_kinds_jsonl_and_bin1(lm, rng):
    """The whole subsystem over real TCP, BOTH protocols: client
    helpers -> wire extras -> server -> engine -> typed done records
    carrying kind/completions/logprobs/embedding."""
    from distkeras_tpu.serving import ServingServer
    from distkeras_tpu.serving.client import ServingClient

    model, variables = lm
    p = _prompt(rng, 6)
    want = _want(lm, p, 4)

    async def go():
        eng = ServingEngine(model, variables, slots=4, max_queue=16,
                            kv_pool_blocks=64, kv_block_tokens=4,
                            constrained=True)
        server = ServingServer(eng, port=0)
        await server.start()
        outs = {}
        for mode in ("jsonl", "bin1"):
            async with ServingClient("127.0.0.1", server.port,
                                     wire_mode=mode) as c:
                sample = await c.sample(p, 4, 2, speculate=False)
                score = await c.score(p)
                embed = await c.embed(p)
                con = await c.generate(
                    p, 4, constraint=_alternating_dfa())
                outs[mode] = (sample, score, embed, con)
                # Contradiction: typed bad_request, not a dead stream.
                from distkeras_tpu.serving.client import _CODE_TO_ERROR
                with pytest.raises(
                        _CODE_TO_ERROR.get("bad_request", Exception)):
                    await c.generate(p, 3, kind="score")
        await server.stop(drain=True)
        return outs

    outs = asyncio.run(go())
    for mode in ("jsonl", "bin1"):
        sample, score, embed, con = outs[mode]
        assert sample["kind"] == "sample"
        assert sample["completions"] == [want, want]
        assert sample["tokens"] == []
        assert score["kind"] == "score"
        assert len(score["logprobs"]) == len(p) - 1
        assert embed["kind"] == "embed"
        assert len(embed["embedding"]) > 0
        assert con["tokens"] == [1, 2, 1, 2]
    # Protocol parity: bin1 and jsonl carried identical payloads.
    assert outs["jsonl"][0]["completions"] == outs["bin1"][0]["completions"]
    np.testing.assert_allclose(outs["jsonl"][1]["logprobs"],
                               outs["bin1"][1]["logprobs"], atol=1e-6)
