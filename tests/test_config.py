import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.utils.config import TrainerConfig


def _model():
    return Model.from_flax(MLP(features=(8,), num_classes=2), input_shape=(4,))


def test_roundtrip_json():
    cfg = TrainerConfig(trainer="ADAG", num_workers=4, communication_window=8)
    back = TrainerConfig.from_json(cfg.to_json())
    assert back == cfg


def test_unknown_trainer_rejected():
    with pytest.raises(ValueError):
        TrainerConfig(trainer="Nope")


def test_build_and_train():
    cfg = TrainerConfig(
        trainer="DOWNPOUR", worker_optimizer="adam", learning_rate=0.01,
        num_workers=2, batch_size=16, num_epoch=2, communication_window=4,
    )
    trainer = cfg.build(_model())
    assert isinstance(trainer, dk.DOWNPOUR)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    trainer.train(dk.Dataset.from_arrays(features=x, label=y))
    assert trainer.parameter_server.num_commits > 0


def test_build_rejects_inapplicable_kwargs():
    cfg = TrainerConfig(trainer="SingleTrainer", num_workers=4)
    with pytest.raises(ValueError, match="num_workers"):
        cfg.build(_model())


@pytest.mark.slow
def test_build_pipeline_trainer():
    from distkeras_tpu.models.bert import BertConfig, _make

    cfg = TrainerConfig(
        trainer="PipelineTrainer", worker_optimizer="adam",
        learning_rate=1e-3, batch_size=16, num_epoch=1,
    )
    bcfg = BertConfig(
        vocab_size=32, hidden_size=32, num_layers=2, num_heads=2,
        mlp_dim=64, max_seq_len=16, dropout_rate=0.0,
    )
    trainer = cfg.build(_make(bcfg, 16, "bp_cfg"))
    assert isinstance(trainer, dk.PipelineTrainer)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 32, size=(64, 16)).astype(np.int32)
    trainer.num_stages = 2
    trained = trainer.train(dk.Dataset.from_arrays(features=toks, label=toks))
    assert np.isfinite(trained.predict(toks[:2])).all()
