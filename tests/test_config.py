import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.models.core import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.utils.config import TrainerConfig


def _model():
    return Model.from_flax(MLP(features=(8,), num_classes=2), input_shape=(4,))


def test_roundtrip_json():
    cfg = TrainerConfig(trainer="ADAG", num_workers=4, communication_window=8)
    back = TrainerConfig.from_json(cfg.to_json())
    assert back == cfg


def test_unknown_trainer_rejected():
    with pytest.raises(ValueError):
        TrainerConfig(trainer="Nope")


def test_build_and_train():
    cfg = TrainerConfig(
        trainer="DOWNPOUR", worker_optimizer="adam", learning_rate=0.01,
        num_workers=2, batch_size=16, num_epoch=2, communication_window=4,
    )
    trainer = cfg.build(_model())
    assert isinstance(trainer, dk.DOWNPOUR)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    trainer.train(dk.Dataset.from_arrays(features=x, label=y))
    assert trainer.parameter_server.num_commits > 0


def test_build_rejects_inapplicable_kwargs():
    cfg = TrainerConfig(trainer="SingleTrainer", num_workers=4)
    with pytest.raises(ValueError, match="num_workers"):
        cfg.build(_model())
